"""Consumer-group rebalance + elastic recovery: the scalable-Deployment
story the reference delegates to Kafka's coordinator (SURVEY §2.7, §5),
reproduced against the in-process broker."""

import pytest

from iotml.stream.broker import Broker
from iotml.stream.group import (GroupConsumer, GroupCoordinator,
                                range_assign, roundrobin_assign)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture
def broker():
    b = Broker()
    b.create_topic("sensor-data", partitions=10)
    for i in range(200):
        b.produce("sensor-data", f"r{i}".encode(), partition=i % 10)
    return b


def test_range_assignor_contiguous_and_balanced():
    a = range_assign(["m1", "m2", "m3"], {"t": 10})
    sizes = sorted(len(v) for v in a.values())
    assert sizes == [3, 3, 4]
    got = sorted(tp for v in a.values() for tp in v)
    assert got == [("t", p) for p in range(10)]
    # contiguity per member
    for parts in a.values():
        ps = [p for _, p in parts]
        assert ps == list(range(ps[0], ps[0] + len(ps)))


def test_roundrobin_assignor_interleaves_topics():
    a = roundrobin_assign(["m1", "m2"], {"t1": 3, "t2": 3})
    assert sorted(len(v) for v in a.values()) == [3, 3]
    got = sorted(tp for v in a.values() for tp in v)
    assert got == [("t1", 0), ("t1", 1), ("t1", 2),
                   ("t2", 0), ("t2", 1), ("t2", 2)]


def test_join_splits_partitions_and_generation_bumps(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    assert len(c1.assignment) == 10
    g1 = coord.generation

    c2 = GroupConsumer(coord, ["sensor-data"])
    assert coord.generation > g1
    # c1 heals itself on next poll and the split covers all partitions
    c1.poll()
    assert len(c1.assignment) == 5 and len(c2.assignment) == 5
    assert sorted(c1.assignment + c2.assignment) == \
        [("sensor-data", p) for p in range(10)]


def test_all_records_consumed_across_members(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    seen = set()
    for c in (c1, c2):
        while True:
            msgs = c.poll()
            if not msgs:
                break
            seen.update(m.value for m in msgs)
    assert len(seen) == 200


def test_graceful_leave_hands_partitions_to_survivor(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    healed = c1.poll()  # absorbs c2's join; sticky positions keep progress

    # c2 consumes some of its share, commits, leaves
    got = c2.poll(30)
    c2.commit()
    c2.close()

    # c1 inherits everything and resumes c2's partitions at the commit
    msgs = list(healed)
    while True:
        chunk = c1.poll()
        if not chunk:
            break
        msgs.extend(chunk)
    assert len(c1.assignment) == 10
    values = set(m.value for m in msgs) | set(m.value for m in got)
    assert len(values) == 200  # no gaps, no redelivery after clean handoff
    assert len(msgs) + len(got) == 200  # ...and exactly once, in fact


def test_crash_triggers_session_timeout_and_redelivery(broker):
    clock = FakeClock()
    coord = GroupCoordinator(broker, "g", session_timeout_s=5.0, clock=clock)
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    healed = list(c1.poll())  # absorbs c2's join; keeps its own progress

    # c2 consumes 40 records but only commits after the first 20
    first = c2.poll(20)
    c2.commit()
    uncommitted = c2.poll(20)
    # ...and crashes: no leave(), no more heartbeats
    clock.t += 6.0

    # survivor's next poll expires the corpse and adopts its partitions
    msgs = list(c1.poll())
    assert c1.rebalances >= 1
    assert len(c1.assignment) == 10
    while True:
        chunk = c1.poll()
        if not chunk:
            break
        msgs.extend(chunk)
    survivor_values = set(m.value for m in msgs)
    # at-least-once: the 20 uncommitted records ARE redelivered
    assert set(m.value for m in uncommitted) <= survivor_values
    # nothing is lost: committed ∪ everything c1 was delivered = all records
    # (sticky positions: c1's pre-crash progress is NOT redelivered to it)
    assert set(m.value for m in first) | set(m.value for m in healed) \
        | survivor_values == {f"r{i}".encode() for i in range(200)}


def test_scale_out_mid_stream_no_duplicates_with_commits(broker):
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    part1 = c1.poll(50)
    c1.commit()

    c2 = GroupConsumer(coord, ["sensor-data"])  # scale-out
    rest = []
    for c in (c1, c2):
        while True:
            chunk = c.poll()
            if not chunk:
                break
            rest.extend(chunk)
    # with a commit before the rebalance, handoff introduces no duplicates
    all_msgs = part1 + rest
    assert len(all_msgs) == 200
    assert len(set(m.value for m in all_msgs)) == 200


def test_heartbeat_rejects_stale_generation(broker):
    coord = GroupCoordinator(broker, "g")
    m1, gen1, _ = coord.join(["sensor-data"])
    coord.join(["sensor-data"])  # second member bumps generation
    assert coord.heartbeat(m1, gen1) is False
    m1b, gen2, assigned = coord.join(["sensor-data"], m1)
    assert m1b == m1 and gen2 == coord.generation
    assert coord.heartbeat(m1, gen2) is True


def test_group_elastic_sensorbatches_pipeline():
    """End-to-end elasticity: two group members run SensorBatches over a
    partitioned framed-Avro sensor stream; one crashes mid-consume; the
    survivor adopts its partitions and the fleet's records all get through
    (at-least-once)."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    b = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=50, failure_rate=0.0))
    total = gen.publish(b, "SENSOR_DATA_S_AVRO", n_ticks=20, partitions=10)
    assert total == 1000

    clock = FakeClock()
    coord = GroupCoordinator(b, "scorers", session_timeout_s=5.0, clock=clock)
    c1 = GroupConsumer(coord, ["SENSOR_DATA_S_AVRO"])
    c2 = GroupConsumer(coord, ["SENSOR_DATA_S_AVRO"])
    pre = len(c1.poll(1))  # heal after c2's join; delivers one record to c1

    b1 = SensorBatches(c1, batch_size=100)
    b2 = SensorBatches(c2, batch_size=100)

    # c2 consumes one drain pass of its share, commits nothing, crashes
    crashed_rows = sum(batch.n_valid for batch in b2)
    assert crashed_rows > 0
    clock.t += 6.0  # session timeout expires the corpse

    survivor_rows = sum(batch.n_valid for batch in b1)
    c1.commit()
    # survivor saw everything c2 never committed; with sticky positions the
    # record already delivered to c1 pre-crash is not delivered twice
    assert survivor_rows + pre == 1000
    assert len(c1.assignment) == 10


def test_group_consumer_fused_native_path_over_wire():
    """GroupConsumer + SensorBatches over a NATIVE wire broker must take
    the fused fetch_decode branch (with and without keep_keys) — the
    in-process Broker has no fetch_decode, so only a wire-backed test
    exercises the kwarg pass-through the fused branch relies on."""
    import numpy as np
    import pytest

    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream import native
    from iotml.stream.kafka_wire import KafkaWireServer

    if native.load() is None:
        pytest.skip("native engine not built")
    from iotml.stream.native_kafka import NativeKafkaBroker

    b = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=50, failure_rate=0.0))
    total = gen.publish(b, "SENSOR_DATA_S_AVRO", n_ticks=20, partitions=4)
    with KafkaWireServer(b) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        try:
            coord = GroupCoordinator(client, "scorers-wire",
                                     session_timeout_s=5.0)
            c1 = GroupConsumer(coord, ["SENSOR_DATA_S_AVRO"])
            rows = sum(batch.n_valid
                       for batch in SensorBatches(c1, batch_size=100))
            assert rows == total
            # keys variant over the same group machinery
            c1.seek_to_start()
            kb = SensorBatches(c1, batch_size=100, keep_keys=True)
            batches = list(kb)
            assert sum(bt.n_valid for bt in batches) == total
            ks = np.concatenate([bt.keys[: bt.n_valid] for bt in batches])
            assert set(np.unique(ks)) == {
                f"electric-vehicle-{i:05d}".encode() for i in range(50)}
        finally:
            client.close()


def test_two_members_alternating_polls_converge(broker):
    """Regression: a rejoin with an unchanged subscription must not bump the
    generation, else two alternating pollers livelock in perpetual mutual
    invalidation and never progress past the last commit."""
    coord = GroupCoordinator(broker, "g")
    c1 = GroupConsumer(coord, ["sensor-data"])
    c2 = GroupConsumer(coord, ["sensor-data"])
    # c2's join invalidated c1 once; after both have healed, polls alternate
    # with no further rebalances and every record is delivered exactly once.
    seen = set()
    for _ in range(40):
        for c in (c1, c2):
            for m in c.poll(16):
                assert m.value not in seen, "duplicate delivery"
                seen.add(m.value)
    assert len(seen) == 200
    assert c1.rebalances + c2.rebalances <= 2
    assert coord.generation <= 3


def test_subscribe_before_topic_exists(broker):
    """Kafka allows subscribing to a not-yet-created topic; membership must
    survive it and pick the topic up (metadata rebalance) once it appears."""
    coord = GroupCoordinator(broker, "g", metadata_max_age_s=0.0)
    c = GroupConsumer(coord, ["late-topic"])
    assert c.assignment == []
    assert c.poll() == []  # heartbeats fine with nothing assigned
    broker.create_topic("late-topic", partitions=3)
    broker.produce("late-topic", b"x", partition=1)
    got = c.poll() or c.poll()  # first poll absorbs the metadata rebalance
    assert [m.value for m in got] == [b"x"]
    assert c.assignment == [("late-topic", p) for p in range(3)]


def test_fenced_member_cannot_regress_commits(broker):
    """Regression: a member that fell behind a rebalance must not clobber
    offsets committed by the partition's current owner (ILLEGAL_GENERATION)."""
    clock = FakeClock()
    coord = GroupCoordinator(broker, "g", session_timeout_s=5.0, clock=clock)
    c1 = GroupConsumer(coord, ["sensor-data"])
    for _ in range(3):
        c1.poll(30)  # advance cursors but do NOT commit
    clock.t += 10.0  # c1's session expires
    c2 = GroupConsumer(coord, ["sensor-data"])
    while not c2.at_end():
        c2.poll(1000)
    assert c2.commit() is True
    end_offsets = {p: broker.committed("g", "sensor-data", p)
                   for p in range(10)}
    # stale c1 shutting down must not write its old cursors over c2's
    assert c1.commit() is False
    c1.close()
    assert {p: broker.committed("g", "sensor-data", p)
            for p in range(10)} == end_offsets


def test_metadata_probe_rate_limited(broker):
    """Heartbeats between metadata sweeps reuse the cached topic view
    (metadata.max.age.ms analogue); the sweep fires once the age expires."""
    clock = FakeClock()
    coord = GroupCoordinator(broker, "g", clock=clock, metadata_max_age_s=5.0)
    c = GroupConsumer(coord, ["sensor-data", "late-topic"])
    assert c.assignment == [("sensor-data", p) for p in range(10)]
    broker.create_topic("late-topic", partitions=2)
    clock.t += 1.0
    c.poll()  # within max age: cached view, no rebalance yet
    assert ("late-topic", 0) not in c.assignment
    clock.t += 5.0
    c.poll()  # sweep runs, sees the new topic, rebalances
    assert ("late-topic", 0) in c.assignment
