"""Real-Keras round trip for the exported h5 artifacts.

The layout-level parity tests (test_models.py) verify the exported HDF5
matches the reference checkpoint field-for-field; this module closes the
loop with an actual Keras load — the consumer the artifact exists for
(reference cardata-v3.py:255-261 saves with Keras and reloads with Keras).
Gated: skipped wherever TensorFlow is not installed.

Keras-version reality check, pinned below as behavior parity: the
reference's checkpoints are tf.keras-2.2.4-era h5 (pre-TF2 single-nested
`inbound_nodes`), which Keras 3 refuses to deserialize — OUR
style="reference" export fails in exactly the same way, and the
style="modern" export (same weights, TF2-era nesting) loads cleanly.

One-command verification (documented in PARITY.md):
    python -m pytest tests/test_h5_keras_interop.py -q
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from iotml.models.autoencoder import CAR_AUTOENCODER  # noqa: E402
from iotml.models.h5_export import autoencoder_params_to_h5  # noqa: E402
from iotml.models.h5_import import autoencoder_params_from_h5  # noqa: E402

REFERENCE_H5 = \
    "/root/reference/models/autoencoder_sensor_anomaly_detection.h5"


def _keras_load(path):
    """Current Keras' best effort at a legacy h5 (load_model falls through
    to the legacy loader in Keras 3; older tf.keras loads it directly)."""
    try:
        return tf.keras.models.load_model(path, compile=False)
    except ValueError:
        from keras.src.legacy.saving import legacy_h5_format
        return legacy_h5_format.load_model_from_hdf5(path, compile=False)


@pytest.fixture(scope="module")
def trained_params():
    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (4, 18), jnp.float32)
    return CAR_AUTOENCODER.init(rng, x)["params"]


def test_reference_style_behaves_exactly_like_reference_artifact(
        tmp_path, trained_params):
    """Whatever this Keras does with the reference's own checkpoint, it
    must do the same with our reference-style export — that IS the parity
    contract for the byte-layout artifact."""
    path = str(tmp_path / "ref_style.h5")
    autoencoder_params_to_h5(trained_params, path,
                             activity_l1=CAR_AUTOENCODER.activity_l1)
    ref_outcome = ours_outcome = "loaded"
    if os.path.exists(REFERENCE_H5):
        try:
            _keras_load(REFERENCE_H5)
        except (ValueError, TypeError):
            ref_outcome = "rejected"
    else:
        pytest.skip("reference checkpoint not present")
    try:
        _keras_load(path)
    except (ValueError, TypeError):
        ours_outcome = "rejected"
    assert ours_outcome == ref_outcome


def test_modern_style_loads_and_predictions_match(tmp_path, trained_params):
    path = str(tmp_path / "car_autoencoder_modern.h5")
    autoencoder_params_to_h5(trained_params, path,
                             activity_l1=CAR_AUTOENCODER.activity_l1,
                             style="modern")
    model = _keras_load(path)
    x = np.random.default_rng(0).uniform(-1, 1, (64, 18)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    flax_out = np.asarray(
        CAR_AUTOENCODER.apply({"params": trained_params}, jnp.asarray(x)))
    # identical float32 weights through identical dense stacks
    np.testing.assert_allclose(keras_out, flax_out, rtol=1e-5, atol=1e-6)


def test_modern_style_architecture_is_the_references(tmp_path,
                                                     trained_params):
    """18 → 14(tanh) → 7(relu) → 7(tanh) → 18(relu) with the activity
    regularizer on the first encoder layer (cardata-v3.py:205-214)."""
    path = str(tmp_path / "arch.h5")
    autoencoder_params_to_h5(trained_params, path,
                             activity_l1=CAR_AUTOENCODER.activity_l1,
                             style="modern")
    model = _keras_load(path)
    dense = [l for l in model.layers if l.__class__.__name__ == "Dense"]
    assert [l.units for l in dense] == [14, 7, 7, 18]
    acts = [getattr(l.activation, "__name__", str(l.activation))
            for l in dense]
    assert acts == ["tanh", "relu", "tanh", "relu"]
    reg = dense[0].activity_regularizer
    assert reg is not None and float(reg.l1) == pytest.approx(
        CAR_AUTOENCODER.activity_l1)


def test_keras_roundtrip_back_to_flax(tmp_path, trained_params):
    """Export → Keras load → Keras save → our importer reads it back."""
    path = str(tmp_path / "exported.h5")
    autoencoder_params_to_h5(trained_params, path,
                             activity_l1=CAR_AUTOENCODER.activity_l1,
                             style="modern")
    model = _keras_load(path)
    resaved = str(tmp_path / "keras_resaved.h5")
    try:
        model.save(resaved, save_format="h5")
    except TypeError:  # Keras 3: format inferred from the extension
        model.save(resaved)
    params = autoencoder_params_from_h5(resaved)
    x = np.random.default_rng(1).uniform(-1, 1, (16, 18)).astype(np.float32)
    a = CAR_AUTOENCODER.apply({"params": trained_params}, jnp.asarray(x))
    b = CAR_AUTOENCODER.apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-7)
