"""Kafka wire protocol: message-set codec, client↔server round trips,
SASL/PLAIN, and the full pipeline over real TCP."""

import numpy as np
import pytest

from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.kafka_wire import (KafkaWireBroker, KafkaWireServer,
                                     decode_message_set, encode_message_set)


def test_message_set_roundtrip_and_crc():
    entries = [(0, b"k1", b"v1", 5), (1, None, b"v2", 6), (2, b"k3", b"", 7)]
    buf = encode_message_set(entries)
    assert decode_message_set(buf) == entries
    # corrupting a value byte must be caught by the CRC
    bad = bytearray(buf)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_message_set(bytes(bad))
    # a truncated trailing message is dropped, not an error
    assert decode_message_set(buf[:-3]) == entries[:2]


def test_message_set_native_codec_byte_parity():
    """The C++ msgset codec must be byte-identical to the Python oracle on
    every edge the wire carries: null/empty keys, empty values, zero and
    large timestamps, real offsets."""
    from iotml.stream import kafka_wire as kw

    if kw._native_lib() is None:
        pytest.skip("native engine not built")
    rng = np.random.default_rng(5)
    entries = [(int(i * 7), None if i % 3 == 0 else
                bytes(rng.integers(0, 256, i % 17, dtype=np.uint8)),
                bytes(rng.integers(0, 256, (i * 13) % 301, dtype=np.uint8)),
                int(1_700_000_000_000 + i)) for i in range(64)]
    entries += [(99, b"", b"", 0)]  # empty (non-null) key and value
    buf_native = kw.encode_message_set(entries)
    buf_py = kw._encode_message_set_py(entries)
    assert buf_native == buf_py
    assert kw.decode_message_set(buf_py) == entries
    assert kw._decode_message_set_py(buf_native) == entries
    # truncated tail: native path drops it exactly like the oracle
    assert kw.decode_message_set(buf_py[:-5]) == \
        kw._decode_message_set_py(buf_py[:-5])


def test_client_server_produce_fetch_offsets():
    backing = Broker()
    with KafkaWireServer(backing) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        client.create_topic("t", partitions=3)
        assert "t" in client.topics()
        assert client.topic("t").partitions == 3
        # keyed produce lands on a stable partition; offsets come back
        off = client.produce("t", b"hello", key=b"car-1")
        assert off == 0
        assert client.produce("t", b"world", key=b"car-1") == 1
        p = [p for p in range(3) if backing.end_offset("t", p) == 2][0]
        msgs = client.fetch("t", p, 0)
        assert [(m.value, m.key) for m in msgs] == \
            [(b"hello", b"car-1"), (b"world", b"car-1")]
        assert client.end_offset("t", p) == 2
        assert client.begin_offset("t", p) == 0
        # fetch from a mid offset
        assert [m.value for m in client.fetch("t", p, 1)] == [b"world"]
        # consumer-group offsets round-trip
        assert client.committed("g", "t", p) is None
        client.commit("g", "t", p, 2)
        assert client.committed("g", "t", p) == 2
        assert backing.committed("g", "t", p) == 2
        client.close()


def test_create_topic_idempotent_and_unknown_fetch():
    with KafkaWireServer(Broker()) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        client.create_topic("t", partitions=2)
        client.create_topic("t", partitions=2)  # TOPIC_EXISTS swallowed
        with pytest.raises(KeyError):
            client.fetch("nope", 0, 0)
        client.close()


def test_sasl_plain_required():
    backing = Broker()
    backing.produce("t", b"secret")
    with KafkaWireServer(backing, credentials=("test", "test123")) as srv:
        ok = KafkaWireBroker(f"127.0.0.1:{srv.port}",
                             sasl_username="test", sasl_password="test123")
        assert [m.value for m in ok.fetch("t", 0, 0)] == [b"secret"]
        ok.close()
        with pytest.raises((ConnectionError, OSError)):
            KafkaWireBroker(f"127.0.0.1:{srv.port}",
                            sasl_username="test", sasl_password="wrong")
        # unauthenticated protocol use is refused outright
        with pytest.raises((ConnectionError, OSError)):
            bad = KafkaWireBroker(f"127.0.0.1:{srv.port}")
            bad.fetch("t", 0, 0)


def test_stream_consumer_over_the_wire():
    """StreamConsumer + SensorBatches run unchanged against the wire client
    — the Broker duck-type contract."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    backing = Broker()
    with KafkaWireServer(backing) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        gen = FleetGenerator(FleetScenario(num_cars=50))
        gen.publish(client, "SENSOR_DATA_S_AVRO", n_ticks=4)  # 200 records
        consumer = StreamConsumer(client, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="wire-test")
        batches = list(SensorBatches(consumer, batch_size=50))
        assert sum(b.n_valid for b in batches) == 200
        assert batches[0].x.shape == (50, 18)
        client.close()


def test_cli_train_predict_against_wire_server(tmp_path):
    """The deploy manifests' exact invocation shape: cardata CLI pointed at
    host:port + SASL env — train then predict against a live wire server."""
    from iotml.cli.cardata import main as cardata_main
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    backing = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
    # predict skips 100 batches then takes 100 (the reference's data_offset
    # split), so partition 0 needs ≥20k records
    gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=210)  # 21k records
    root = str(tmp_path / "artifacts")
    with KafkaWireServer(backing, credentials=("svc", "pw")) as srv:
        argv = [f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO", "0",
                "model-predictions", "train", "model1", root,
                "--broker.sasl_username=svc", "--broker.sasl_password=pw",
                "--train.epochs=2"]
        assert cardata_main(argv) == 0
        argv[4] = "predict"
        assert cardata_main(argv) == 0
        # ordered write-back landed on the real (backing) log
        n = backing.end_offset("model-predictions", 0)
        assert n == 100 * 100  # PREDICT take(100) × batch(100)
        first = backing.fetch("model-predictions", 0, 0, 1)[0]
        assert first.value.startswith(b"[")


def test_cross_process_consumer_groups_over_wire():
    """Elastic consumer groups across the wire protocol: two independent
    clients (as if separate pods) join the same group via JoinGroup/
    SyncGroup, split partitions disjointly, heartbeat, commit fenced, and a
    leave hands partitions to the survivor — the reference's scalable-
    Deployment story with membership living broker-side."""
    from iotml.stream.broker import Broker
    from iotml.stream.group import GroupConsumer
    from iotml.stream.kafka_wire import (KafkaWireBroker, KafkaWireServer,
                                         RemoteGroupCoordinator)

    broker = Broker()
    broker.create_topic("t", partitions=6)
    for i in range(120):
        broker.produce("t", f"r{i}".encode(), partition=i % 6)

    with KafkaWireServer(broker) as server:
        addr = f"127.0.0.1:{server.port}"
        client1, client2 = KafkaWireBroker(addr), KafkaWireBroker(addr)
        c1 = GroupConsumer(RemoteGroupCoordinator(client1, "g"), ["t"])
        c2 = GroupConsumer(RemoteGroupCoordinator(client2, "g"), ["t"])
        healed = c1.poll(1)  # heal after c2's join (sticky: delivered once)

        assert len(c1.assignment) == 3 and len(c2.assignment) == 3
        assert sorted(c1.assignment + c2.assignment) == \
            [("t", p) for p in range(6)]

        seen = set(m.value for m in healed)
        for c in (c1, c2):
            while True:
                msgs = c.poll()
                if not msgs:
                    break
                seen.update(m.value for m in msgs)
        assert len(seen) == 120

        # fenced commits over the wire: both succeed at their generation
        assert c1.commit() is True and c2.commit() is True
        committed = sum(broker.committed("g", "t", p) or 0 for p in range(6))
        assert committed == 120

        # graceful leave: survivor inherits everything at the commits
        c2.close()
        c1.poll()
        assert len(c1.assignment) == 6

        # a stale-generation commit from a fenced member writes nothing
        assert client2.commit_fenced("g", 1, "ghost",
                                     [("t", 0, 0)]) is False
        assert broker.committed("g", "t", 0) is not None

        client1.close()
        client2.close()


def test_fenced_commit_flags_unowned_partitions():
    """A valid-generation commit naming a partition outside the member's
    assignment must error for that partition, not silently drop it."""
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import (KafkaWireBroker, KafkaWireServer,
                                         RemoteGroupCoordinator)

    broker = Broker()
    broker.create_topic("t", partitions=4)
    with KafkaWireServer(broker) as server:
        c1 = KafkaWireBroker(f"127.0.0.1:{server.port}")
        c2 = KafkaWireBroker(f"127.0.0.1:{server.port}")
        r1 = RemoteGroupCoordinator(c1, "g")
        r2 = RemoteGroupCoordinator(c2, "g")
        m1, g1, a1 = r1.join(["t"])
        m2, g2, a2 = r2.join(["t"])
        m1, g1, a1 = r1.join(["t"], m1)  # heal to the current generation
        other = a2[0]  # a partition owned by the peer
        assert c1.commit_fenced("g", g1, m1,
                                [(other[0], other[1], 5)]) is False
        assert broker.committed("g", other[0], other[1]) is None
        # empty-positions commit still reports fencing truthfully
        assert r1.fenced_commit(m1, g1, []) is True
        assert r1.fenced_commit(m1, g1 - 1, []) is False
        c1.close(); c2.close()


def test_bootstrap_server_failover():
    """bootstrap.servers semantics: unreachable entries are skipped, the
    first answering broker wins; all-dead lists raise."""
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    broker = Broker()
    broker.produce("t", b"x")
    with KafkaWireServer(broker) as srv:
        client = KafkaWireBroker(
            f"127.0.0.1:1, 127.0.0.1:{srv.port}", timeout_s=2.0)
        assert [m.value for m in client.fetch("t", 0, 0)] == [b"x"]
        client.close()
    with pytest.raises(OSError):
        KafkaWireBroker("127.0.0.1:1,127.0.0.1:2", timeout_s=1.0)


def test_parse_bootstrap_handles_malformed_and_ipv6():
    from iotml.utils.net import parse_bootstrap

    assert parse_bootstrap("a:1, b ,c:9O92,d:2") == \
        [("a", 1), ("b", 9092), ("d", 2)]
    assert parse_bootstrap("[::1]:3,[fe80::1]") == \
        [("::1", 3), ("fe80::1", 9092)]
    assert parse_bootstrap(",,") == []


def test_failover_retries_idempotent_apis_only():
    """A reconnect auto-retries reads (fetch/metadata) transparently, but
    surfaces ConnectionError for non-idempotent produce/commit — the dead
    server may have applied them, and a blind retry double-applies
    (ADVICE.md round-5).  The client is reconnected afterwards, so the
    caller opts into redelivery with a plain re-call."""
    b1, b2 = Broker(), Broker()
    for b in (b1, b2):
        b.create_topic("t", partitions=1)
        b.produce("t", b"seed")
    s1 = KafkaWireServer(b1).start()
    s2 = KafkaWireServer(b2).start()
    try:
        client = KafkaWireBroker(f"127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                                 timeout_s=5.0)
        assert client.produce("t", b"on-leader") == 1
        s1.kill()
        # non-idempotent: surfaced, not silently retried
        with pytest.raises(ConnectionError, match="non-idempotent"):
            client.produce("t", b"during-failover")
        # ...but the failover reconnect already happened: an explicit
        # redelivery lands on the follower
        assert client.produce("t", b"redelivered") == 1
        with pytest.raises(ConnectionError):
            # commit rides OffsetCommit: same contract
            s2.kill()
            client.commit("g", "t", 0, 1)
    finally:
        for s in (s1, s2):
            try:
                s.server_close()
            except OSError:
                pass


def test_failover_fetch_is_transparent():
    """The idempotent side of the same contract: a fetch that hits a dead
    socket fails over and answers from the next bootstrap server without
    the caller noticing."""
    b1, b2 = Broker(), Broker()
    for b in (b1, b2):
        b.create_topic("t", partitions=1)
        for i in range(3):
            b.produce("t", f"m{i}".encode())
    s1 = KafkaWireServer(b1).start()
    s2 = KafkaWireServer(b2).start()
    try:
        client = KafkaWireBroker(f"127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                                 timeout_s=5.0)
        assert len(client.fetch("t", 0, 0)) == 3
        s1.kill()
        assert [m.value for m in client.fetch("t", 0, 0)] == \
            [b"m0", b"m1", b"m2"]
        assert client.end_offset("t") == 3
    finally:
        for s in (s1, s2):
            try:
                s.server_close()
            except OSError:
                pass


def test_committed_many_one_round_trip():
    """committed_many fetches every (topic, partition) of a group in ONE
    OffsetFetch request, omitting uncommitted pairs — the replica's
    commit-mirror batching."""
    broker = Broker()
    broker.create_topic("A", partitions=3)
    broker.create_topic("B", partitions=2)
    with KafkaWireServer(broker) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        client.commit("g", "A", 0, 10)
        client.commit("g", "A", 2, 30)
        client.commit("g", "B", 1, 5)
        pairs = [("A", p) for p in range(3)] + [("B", p) for p in range(2)]
        before = client._corr
        got = client.committed_many("g", pairs)
        assert client._corr == before + 1  # exactly one wire request
        assert got == {("A", 0): 10, ("A", 2): 30, ("B", 1): 5}
        # parity with the single-pair path
        assert client.committed("g", "A", 1) is None
        client.close()
