"""Continuous train → artifact pointer → live scorer hot-swap.

The closed loop the reference sequences with run.sh (train Job uploads to
GCS, predict pods download on restart, cardata-v3.py:227-232,255-261):
here the trainer publishes an immutable versioned h5 + atomic pointer per
round and the long-lived scorer swaps weights between super-batches, with
detection quality accounted live against stream labels.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.serve.live import LiveScorer
from iotml.serve.scorer import StreamScorer
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.producer import OutputSequence
from iotml.train.artifacts import ArtifactStore
from iotml.train.live import ContinuousTrainer


def _seed(broker, n_records, failure_rate=0.02, partitions=2):
    gen = FleetGenerator(FleetScenario(num_cars=100,
                                       failure_rate=failure_rate))
    return gen.publish(broker, "SENSOR_DATA_S_AVRO",
                       n_ticks=n_records // 100, partitions=partitions)


# ----------------------------------------------------------------- trainer
def test_continuous_trainer_rounds_pointer_and_resume(tmp_path):
    broker = Broker()
    _seed(broker, 3000)
    store = ArtifactStore(str(tmp_path))
    tr = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO", store,
                           take_batches=10, group="t-live")
    assert tr.available() == 3000
    ran = tr.run(max_rounds=2)
    assert ran == 2 and tr.rounds == 2
    assert tr.records_trained == 2000
    assert np.isfinite(tr.last_loss)
    # immutable per-round blobs + pointer at the newest
    assert store.exists("cardata-live.h5.r1")
    assert store.exists("cardata-live.h5.r2")
    assert store.get_text("cardata-live.h5.latest") == "cardata-live.h5.r2"
    # committed cursor advanced: a NEW trainer resumes past the consumed
    # slice (the `committed` resume contract)
    consumed = 3000 - tr.available()
    assert consumed >= 2000
    tr2 = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO", store,
                            take_batches=10, group="t-live")
    assert tr2.available() == tr.available()


def test_trainer_waits_for_min_available(tmp_path):
    broker = Broker()
    _seed(broker, 500)  # below the 10x100x1.1 threshold
    store = ArtifactStore(str(tmp_path))
    tr = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO", store,
                           take_batches=10, group="t-wait")
    done = tr.run(stop=lambda: True)  # one pass through the loop
    assert done == 0 and tr.rounds == 0
    assert store.get_text("cardata-live.h5.latest") is None


# ------------------------------------------------------------ quality math
def test_scorer_quality_confusion_counts():
    broker = Broker()
    n = _seed(broker, 2000, failure_rate=0.05)
    n_true = sum(
        1 for p in range(2) for m in broker.fetch("SENSOR_DATA_S_AVRO", p,
                                                  0, 10_000)
        if b"true" in m.value[-12:])
    assert 0 < n_true < n

    def scorer_with(threshold):
        c = StreamConsumer(broker, [f"SENSOR_DATA_S_AVRO:{p}:0"
                                    for p in range(2)])
        broker.create_topic("preds")
        return StreamScorer(
            CAR_AUTOENCODER,
            CAR_AUTOENCODER.init(__import__("jax").random.PRNGKey(0),
                                 np.zeros((1, 18), np.float32))["params"],
            SensorBatches(c, batch_size=100, keep_labels=True),
            OutputSequence(broker, "preds", partition=0),
            threshold=threshold)

    # threshold below any reconstruction error: every row flagged
    s = scorer_with(-1.0)
    assert s.score_available() == n
    assert s.quality == {"tp": n_true, "fp": n - n_true, "fn": 0, "tn": 0}
    # threshold above any error: nothing flagged
    s = scorer_with(1e9)
    s.score_available()
    assert s.quality == {"tp": 0, "fp": 0, "fn": n_true, "tn": n - n_true}


# ---------------------------------------------------------------- hot swap
def test_set_params_mid_drain_no_drop_no_reorder():
    """Swap weights BETWEEN super-batches of one drain: every input row
    still produces exactly one prediction, in order, and rows after the
    swap reflect the new weights."""
    import jax

    broker = Broker()
    n = _seed(broker, 2000, failure_rate=0.0, partitions=1)
    broker.create_topic("preds", partitions=1)
    c = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    params_a = CAR_AUTOENCODER.init(jax.random.PRNGKey(0),
                                    np.zeros((1, 18), np.float32))["params"]
    params_b = jax.tree.map(np.zeros_like, params_a)  # output == bias == 0

    class SwappingScorer(StreamScorer):
        max_super_batches = 4  # force multiple super-batches per drain

        def _score_super_batch(self, bs, base):
            super()._score_super_batch(bs, base)
            if self.scored >= 800 and self.params is params_a:
                self.set_params(params_b)

    s = SwappingScorer(CAR_AUTOENCODER, params_a,
                       SensorBatches(c, batch_size=100),
                       OutputSequence(broker, "preds", partition=0))
    assert s.score_available() == n
    msgs = broker.fetch("preds", 0, 0, 10_000)
    assert len(msgs) == n  # nothing dropped, nothing duplicated
    # the tail (scored with the zero params) is the all-zeros row; the
    # head (params_a) is not
    assert not msgs[0].value.startswith(b"[0. 0. 0. 0.")
    assert msgs[-1].value.startswith(b"[0. 0. 0. 0.")
    # order preserved: rows flip from params_a output to params_b output
    # exactly once (no interleaving across the swap point)
    zeros = [m.value.startswith(b"[0. 0. 0. 0.") for m in msgs]
    flips = sum(1 for i in range(1, n) if zeros[i] != zeros[i - 1])
    assert flips == 1


def test_bounded_drain_resumes_without_loss():
    """max_rows truncation must suspend the drain, not abandon it: every
    buffered row is scored by later calls (no loss, contiguous output)
    and offsets commit only once the drain completes."""
    import jax

    broker = Broker()
    n = _seed(broker, 5000, failure_rate=0.0, partitions=3)
    broker.create_topic("preds", partitions=1)
    c = StreamConsumer(broker, [f"SENSOR_DATA_S_AVRO:{p}:0"
                                for p in range(3)], group="bounded")
    params = CAR_AUTOENCODER.init(jax.random.PRNGKey(0),
                                  np.zeros((1, 18), np.float32))["params"]
    s = StreamScorer(CAR_AUTOENCODER, params,
                     SensorBatches(c, batch_size=100),
                     OutputSequence(broker, "preds", partition=0))
    # small super-batches so the max_rows bound actually bites (the bound
    # is checked per super-batch, default 128x100 rows)
    s.max_super_batches = 4
    total = 0
    calls = 0
    while True:
        got = s.score_available(max_rows=700)
        if not got:
            break
        total += got
        calls += 1
        if s._resume is not None:
            # truncated: the cursor must NOT be committed yet
            assert broker.committed("bounded", "SENSOR_DATA_S_AVRO", 0) \
                is None or total == n
    assert calls > 1          # the bound actually triggered
    assert total == n         # nothing lost across truncations
    msgs = broker.fetch("preds", 0, 0, 10_000)
    assert len(msgs) == n     # one prediction per input row, no gaps
    # drain completed → offsets committed at the stream end
    committed = sum(broker.committed("bounded", "SENSOR_DATA_S_AVRO", p)
                    for p in range(3))
    assert committed == n


def test_live_scorer_hotswap_from_store(tmp_path):
    broker = Broker()
    _seed(broker, 3000, failure_rate=0.05)
    broker.create_topic("model-predictions", partitions=1)
    store = ArtifactStore(str(tmp_path))
    tr = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO", store,
                           take_batches=10, group="t-hs")
    sc = LiveScorer(broker, "SENSOR_DATA_S_AVRO", "model-predictions",
                    store, threshold=5.0, group="s-hs")
    with pytest.raises(TimeoutError):
        sc.wait_for_model(timeout_s=0.2)  # nothing published yet
    tr.run(max_rounds=1)
    assert sc.wait_for_model() == "cardata-live.h5.r1"
    assert sc.model_updates == 1
    n = sc.scorer.score_available()
    assert n == 3000  # scores everything incl. failure rows
    q = sc.scorer.quality
    assert sum(q.values()) == 3000
    tr.run(max_rounds=1)
    assert sc.maybe_swap() and sc.model_updates == 2
    assert sc._current_artifact == "cardata-live.h5.r2"
    assert not sc.maybe_swap()  # pointer unchanged → no re-download


# ------------------------------------------------------------------- CLI
def test_live_cli_train_and_score_over_wire(tmp_path):
    """Both services as real OS processes over the Kafka wire — the
    deploy manifests' pod separation (model-training.yaml /
    model-predictions.yaml) driven end to end."""
    from iotml.stream.kafka_wire import KafkaWireServer

    broker = Broker()
    _seed(broker, 4000, failure_rate=0.05)
    broker.create_topic("model-predictions", partitions=1)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_"))}
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo})

    with KafkaWireServer(broker) as srv:
        addr = f"127.0.0.1:{srv.port}"
        root = str(tmp_path)
        train = subprocess.Popen(
            [sys.executable, "-m", "iotml.cli.live", "train", addr,
             "SENSOR_DATA_S_AVRO", root, "--take-batches", "10",
             "--stats", "--max-seconds", "60"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            cwd=repo, text=True)
        score = subprocess.Popen(
            [sys.executable, "-m", "iotml.cli.live", "score", addr,
             "SENSOR_DATA_S_AVRO", "model-predictions", root,
             "--stats", "--max-seconds", "60"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            cwd=repo, text=True)
        try:
            # trainer: 3 full rounds available (4000 records, 1000/round)
            deadline = time.time() + 90
            while time.time() < deadline and \
                    store_rounds(tmp_path) < 3:
                time.sleep(0.2)
            assert store_rounds(tmp_path) >= 3
            # scorer: predictions flowing
            while time.time() < deadline and \
                    broker.end_offset("model-predictions", 0) < 4000:
                time.sleep(0.2)
            assert broker.end_offset("model-predictions", 0) == 4000
            for proc in (train, score):
                proc.stdin.write("STOP\n")
                proc.stdin.flush()
            t_out, _ = train.communicate(timeout=30)
            s_out, _ = score.communicate(timeout=30)
        finally:
            for proc in (train, score):
                if proc.poll() is None:
                    proc.kill()
        assert train.returncode == 0, t_out
        assert score.returncode == 0, s_out
        # stats lines parse and carry the closed-loop evidence
        t_stats = [json.loads(l) for l in t_out.splitlines()
                   if l.startswith("{")]
        s_stats = [json.loads(l) for l in s_out.splitlines()
                   if l.startswith("{")]
        assert t_stats and t_stats[-1]["round"] >= 3
        assert s_stats
        last = s_stats[-1]
        assert last["scored"] == 4000
        assert sum(last["quality"].values()) == 4000
        assert last["model_updates"] >= 1
        assert last["artifact"].startswith("cardata-live.h5.r")
        # predictions carry the threshold verdict (reference payload +
        # |verdict|mse suffix)
        m = broker.fetch("model-predictions", 0, 0, 1)[0]
        assert m.value.startswith(b"[") and b"|" in m.value


def store_rounds(tmp_path) -> int:
    try:
        with open(os.path.join(str(tmp_path),
                               "cardata-live.h5.latest")) as fh:
            return int(fh.read().rsplit(".r", 1)[1])
    except (FileNotFoundError, ValueError, IndexError):
        return 0
