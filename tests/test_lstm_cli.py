"""LSTM streaming CLI: train → artifact store → predict write-back."""

import numpy as np

from iotml.cli.lstm import main as lstm_main
from iotml.stream.broker import Broker


def test_lstm_train_then_predict(tmp_path, capsys):
    root = str(tmp_path / "artifacts")
    rc = lstm_main(["emulator:4000", "SENSOR_DATA_S_AVRO", "0",
                    "model-predictions", "train", "lstm1", root])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Training complete" in out and "stored successfully" in out

    rc = lstm_main(["emulator:4000", "SENSOR_DATA_S_AVRO", "0",
                    "model-predictions", "predict", "lstm1", root])
    assert rc == 0
    out = capsys.readouterr().out
    assert "predict complete" in out
    # emulator is per-invocation, so write-back is proven by the end-offset
    # line reporting a non-zero result topic
    assert "end offset" in out
    n = int(out.rsplit("end offset", 1)[1].strip().rstrip(")"))
    assert n > 0


def test_lstm_cli_usage_errors(capsys):
    assert lstm_main([]) == 1
    assert "usage" in capsys.readouterr().out
    assert lstm_main(["e", "t", "0", "r", "bogus", "m", "a"]) == 1
    assert "invalid" in capsys.readouterr().out


def test_cardata_train_sharded_mesh(tmp_path, capsys):
    """--mesh.* flags route training through ShardedTrainer over a
    ('data','model') mesh — the deploy manifests' IOTML_MESH_DATA path."""
    from iotml.cli.cardata import main as cardata_main

    root = str(tmp_path / "artifacts")
    rc = cardata_main(["emulator:12000", "SENSOR_DATA_S_AVRO", "0",
                       "model-predictions", "train", "m", root,
                       "--mesh.data=4", "--mesh.model=2",
                       "--train.epochs=2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mesh: {'data': 4, 'model': 2}" in out
    assert "Training complete" in out


def test_cardata_cli_committed_offset_and_partition_share(monkeypatch, tmp_path):
    """The multi-host manifest contract: <offset>='committed' resumes from
    the group cursor, and JAX_NUM_PROCESSES/JAX_PROCESS_ID split the topic's
    partitions across pods (deploy/model-training-multihost.yaml)."""
    from iotml.cli import cardata

    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    rc = cardata.main(["emulator:2000", "SENSOR_DATA_S_AVRO", "committed",
                       "model-predictions", "train", "m1",
                       str(tmp_path / "artifacts"),
                       "--train.epochs=1", "--train.take_batches=5"])
    assert rc == 0
    assert (tmp_path / "artifacts").exists()


def test_train_commits_offsets_for_committed_resume(tmp_path):
    """After a successful train, the group cursor is committed (post-
    checkpoint), so a rerun with <offset>='committed' resumes past the
    already-trained slice instead of re-reading from 0."""
    from iotml.cli import cardata
    from iotml.cli._app import _broker_for
    from iotml.config import load_config

    # use one shared emulator broker via monkeypatching _broker_for? simpler:
    # run against the in-process broker through the wire server
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    broker = Broker()
    FleetGenerator(FleetScenario(num_cars=20, failure_rate=0.0)).publish(
        broker, "SENSOR_DATA_S_AVRO", n_ticks=30)  # 600 records
    with KafkaWireServer(broker) as server:
        servers = f"127.0.0.1:{server.port}"
        args = [servers, "SENSOR_DATA_S_AVRO", "committed",
                "model-predictions", "train", "m1", str(tmp_path / "a"),
                "--train.epochs=1", "--train.take_batches=4",
                "--train.batch_size=100"]
        assert cardata.main(list(args)) == 0
        committed = broker.committed("cardata-autoencoder",
                                     "SENSOR_DATA_S_AVRO", 0)
        assert committed is not None and committed >= 400
        # rerun resumes at the committed cursor: only 200 records remain
        assert cardata.main(list(args)) == 0
        committed2 = broker.committed("cardata-autoencoder",
                                      "SENSOR_DATA_S_AVRO", 0)
        assert committed2 == 600


def test_cardata_h5_artifact_contract(tmp_path):
    """VERDICT r1: an '.h5' model-file name keeps the reference's artifact
    format — train stores a Keras h5 blob (loadable by reference-side
    Keras), predict loads it back and scores."""
    import h5py

    from iotml.cli.cardata import main as cardata_main

    root = str(tmp_path / "artifacts")
    rc = cardata_main(["emulator:12000", "SENSOR_DATA_S_AVRO", "0",
                       "model-predictions", "train", "model1.h5", root,
                       "--train.epochs=2"])
    assert rc == 0
    stored = tmp_path / "artifacts" / "model1.h5"
    assert stored.is_file()
    with h5py.File(stored, "r") as f:  # a real Keras h5, not orbax
        assert "model_config" in f.attrs
        assert "model_weights" in f
    rc = cardata_main(["emulator:12000", "SENSOR_DATA_S_AVRO", "0",
                       "model-predictions", "predict", "model1.h5", root])
    assert rc == 0
