"""MQTT Last Will & Testament + keepalive enforcement.

The reference broker is full HiveMQ MQTT 5 (reference
infrastructure/hivemq/hivemq-crd.yaml:10-26): a client registers a will at
CONNECT and the broker publishes it when the connection dies without a
clean DISCONNECT — the failure-detection primitive a predictive-maintenance
fleet relies on (a dead car's will tells the platform the car is gone).
These tests drive both TCP fronts end to end over real sockets.
"""

import socket
import struct
import threading
import time

import pytest

from iotml.mqtt.broker import MqttBroker, QueueClient
from iotml.mqtt.eventserver import MqttEventServer
from iotml.mqtt.wire import (CONNACK, DISCONNECT, MqttClient, MqttServer,
                             connect_packet, packet)


def _wait_for(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def _collector():
    got = []
    lock = threading.Lock()

    def on_message(topic, payload):
        with lock:
            got.append((topic, payload))

    return got, on_message


def _raw_connect(port, connect_bytes):
    """Open a raw socket, send CONNECT, read past the CONNACK.  Raw because
    MqttClient auto-pings its keepalive — these tests need a client that
    goes silent or crafts packets byte-for-byte."""
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(connect_bytes)
    ack = s.recv(64)
    assert ack and ack[0] >> 4 == CONNACK
    return s


@pytest.fixture(params=["threaded", "event"])
def server(request):
    broker = MqttBroker()
    cls = MqttServer if request.param == "threaded" else MqttEventServer
    with cls(broker) as s:
        yield broker, s


class TestWill:
    def test_will_published_on_socket_drop(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        dying = MqttClient("127.0.0.1", s.port, "dying-car",
                           will=("wills/dying-car", b"gone", 0, False))
        _wait_for(lambda: broker.session_count() == 2)
        dying.drop()  # no DISCONNECT: abnormal
        assert _wait_for(lambda: ("wills/dying-car", b"gone") in got)
        watcher.disconnect()

    def test_no_will_on_clean_disconnect(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        leaving = MqttClient("127.0.0.1", s.port, "leaving-car",
                             will=("wills/leaving-car", b"gone", 0, False))
        _wait_for(lambda: broker.session_count() == 2)
        leaving.disconnect()  # clean: will must be discarded
        _wait_for(lambda: broker.session_count() == 1)
        time.sleep(0.3)
        assert got == []
        watcher.disconnect()

    def test_will_v5_with_qos_and_retain(self, server):
        broker, s = server
        dying = MqttClient("127.0.0.1", s.port, "car-v5", protocol_level=5,
                           will=("wills/car-v5", b"lost", 1, True))
        _wait_for(lambda: broker.session_count() == 1)
        dying.drop()
        # retain flag on the will: a late subscriber still sees it
        assert _wait_for(
            lambda: broker.retained().get("wills/car-v5") == b"lost")

    def test_will_published_on_takeover(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        first = MqttClient("127.0.0.1", s.port, "shared-id",
                           will=("wills/shared-id", b"superseded", 0, False))
        _wait_for(lambda: broker.session_count() == 2)
        second = MqttClient("127.0.0.1", s.port, "shared-id")
        assert _wait_for(
            lambda: ("wills/shared-id", b"superseded") in got)
        # the superseded connection's teardown must not re-publish
        first.drop()
        time.sleep(0.3)
        assert got.count(("wills/shared-id", b"superseded")) == 1
        second.disconnect()
        watcher.disconnect()

    def test_v5_disconnect_with_will_reason_keeps_will(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        raw = _raw_connect(s.port, connect_packet(
            "v5-willful", protocol_level=5,
            will=("wills/v5-willful", b"still-told", 0, False)))
        _wait_for(lambda: broker.session_count() == 2)
        # DISCONNECT reason 0x04 = "disconnect with will message" (§3.14.2.1)
        raw.sendall(packet(DISCONNECT, 0, b"\x04\x00"))
        raw.close()
        assert _wait_for(lambda: ("wills/v5-willful", b"still-told") in got)
        watcher.disconnect()


class TestWillDelay:
    def test_delayed_will_cancelled_by_reconnect(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        flaky = MqttClient("127.0.0.1", s.port, "flaky", protocol_level=5,
                           clean=False,
                           will=("wills/flaky", b"gone", 0, False),
                           will_delay_s=30)
        _wait_for(lambda: broker.session_count() == 2)
        flaky.drop()
        _wait_for(lambda: broker.session_count() == 1)
        # reconnect within the delay cancels the pending will
        again = MqttClient("127.0.0.1", s.port, "flaky", protocol_level=5,
                           clean=False)
        time.sleep(0.3)
        assert got == []
        again.disconnect()
        watcher.disconnect()

    def test_delayed_will_fires_after_delay(self):
        # broker-level: the sweep that fires due wills runs on broker
        # activity, so drive it directly (transport-independent semantics)
        broker = MqttBroker()
        watcher = QueueClient(broker, "watcher")
        watcher.subscribe("wills/#")
        sess = broker.connect("flaky", lambda *a: None, clean_start=False,
                              will=("wills/flaky", b"gone", 0, False),
                              will_delay_s=0.2)
        broker.disconnect("flaky", sess)  # abnormal (will still set)
        assert watcher.messages == []    # not yet: delay pending
        time.sleep(0.3)
        QueueClient(broker, "sweeper").disconnect()  # any activity sweeps
        assert ("wills/flaky", b"gone", 0, False) in watcher.messages

    def test_delayed_will_fires_on_clean_start_reconnect(self):
        """A clean-start CONNECT ends the old session rather than resuming
        it, so a pending delayed will fires immediately (§3.1.2.5: earlier
        of delay expiry and session end) — a crashed device re-provisioned
        clean within the delay window must still report as dead."""
        broker = MqttBroker()
        watcher = QueueClient(broker, "watcher")
        watcher.subscribe("wills/#")
        sess = broker.connect("flaky", lambda *a: None, clean_start=False,
                              will=("wills/flaky", b"gone", 0, False),
                              will_delay_s=30)
        broker.disconnect("flaky", sess)  # abnormal → will pending 30 s
        assert watcher.messages == []
        broker.connect("flaky", lambda *a: None, clean_start=True)
        assert ("wills/flaky", b"gone", 0, False) in watcher.messages

    def test_delayed_will_fires_on_clean_start_takeover(self):
        """Clean-start takeover of a LIVE session with a will delay: the
        old session ends now, so its will publishes now (the non-clean
        takeover path instead cancels it, §3.1.3.2.2)."""
        broker = MqttBroker()
        watcher = QueueClient(broker, "watcher")
        watcher.subscribe("wills/#")
        broker.connect("flaky", lambda *a: None, clean_start=False,
                       will=("wills/flaky", b"dead", 0, False),
                       will_delay_s=30)
        broker.connect("flaky", lambda *a: None, clean_start=True)
        assert ("wills/flaky", b"dead", 0, False) in watcher.messages

    def test_delayed_will_fires_on_quiet_broker(self):
        """No connects/publishes after the drop: the timer alone must fire
        the will — a silent fleet is exactly what a will reports."""
        broker = MqttBroker()
        watcher = QueueClient(broker, "watcher")
        watcher.subscribe("wills/#")
        sess = broker.connect("flaky", lambda *a: None, clean_start=False,
                              will=("wills/flaky", b"gone", 0, False),
                              will_delay_s=0.3)
        broker.disconnect("flaky", sess)
        assert watcher.messages == []
        assert _wait_for(lambda: ("wills/flaky", b"gone", 0, False)
                         in watcher.messages, timeout=3.0)


class TestKeepalive:
    def test_keepalive_eviction_publishes_will(self, server):
        broker, s = server
        got, on_message = _collector()
        watcher = MqttClient("127.0.0.1", s.port, "watcher",
                             on_message=on_message)
        watcher.subscribe("wills/#")
        raw = _raw_connect(s.port, connect_packet(
            "silent-car", keepalive=1,
            will=("wills/silent-car", b"timed-out", 0, False)))
        _wait_for(lambda: broker.session_count() == 2)
        # no packets for >1.5×keepalive: the front must evict and the
        # broker publish the will (sweep cadence adds up to ~1s on the
        # event front)
        assert _wait_for(
            lambda: ("wills/silent-car", b"timed-out") in got, timeout=6.0)
        assert broker.session_count() == 1
        raw.close()
        watcher.disconnect()

    def test_keepalive_zero_disables_eviction(self, server):
        broker, s = server
        raw = _raw_connect(s.port, connect_packet("immortal", keepalive=0))
        _wait_for(lambda: broker.session_count() == 1)
        time.sleep(2.0)
        assert broker.session_count() == 1
        raw.close()

    def test_active_client_survives_keepalive(self, server):
        broker, s = server
        raw = _raw_connect(s.port, connect_packet("pinger", keepalive=1))
        _wait_for(lambda: broker.session_count() == 1)
        # PINGREQ within every keepalive window: must stay connected
        from iotml.mqtt.wire import PINGREQ
        for _ in range(4):
            time.sleep(0.6)
            raw.sendall(packet(PINGREQ, 0, b""))
        assert broker.session_count() == 1
        raw.close()

    def test_eventserver_drops_silent_preconnect_socket(self):
        """A socket that never sends CONNECT must not hold its fd forever
        on the epoll front (the threaded front bounds this at 30s)."""
        broker = MqttBroker()
        with MqttEventServer(broker, handshake_timeout_s=1.0) as s:
            raw = socket.create_connection(("127.0.0.1", s.port), timeout=5)
            _wait_for(lambda: s.connection_count == 1)
            assert _wait_for(lambda: s.connection_count == 0, timeout=5.0)
            raw.close()

    def test_client_autopings_under_keepalive(self, server):
        broker, s = server
        c = MqttClient("127.0.0.1", s.port, "auto", keepalive=1)
        _wait_for(lambda: broker.session_count() == 1)
        time.sleep(2.5)  # > 1.5×keepalive of user silence
        assert broker.session_count() == 1  # auto-ping kept it alive
        c.disconnect()
