"""iotml.mlops — versioned registry, async checkpointing, hot-swap
rollout, rollback gate, and the trainer crash/resume contract.

The ISSUE-7 checklist drives the crash cases: a publish killed between
artifact staging and the manifest leaves a torn (manifest-less) version
dir that readers never see and recover() sweeps; a restarted trainer
resumes model + stream cursors from the last DURABLE manifest — no gap,
no double-train — and manifest cursors beat backfill_since_ms for their
partitions (PR 5 interaction).  The live drills and chaos scenarios
cover the threaded / under-load shapes; these tests pin the unit
semantics deterministically (write_once-driven, no writer thread).
"""

import json
import os
import shutil
import time

import numpy as np
import pytest

from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.mlops import (ABRollout, AsyncCheckpointer, Manifest,
                         ModelRegistry, RegistryWatcher, RolloutGate,
                         restore_trainer)
from iotml.mlops.checkpoint import (params_from_h5_bytes,
                                    params_to_h5_bytes)
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.stream.broker import Broker
from iotml.train.live import ContinuousTrainer
from iotml.train.loop import Trainer

TOPIC = "SENSOR_DATA_S_AVRO"


def _seed(broker, n_records, failure_rate=0.02, partitions=2):
    gen = FleetGenerator(FleetScenario(num_cars=100,
                                       failure_rate=failure_rate))
    return gen.publish(broker, TOPIC, n_ticks=n_records // 100,
                       partitions=partitions)


def _params(seed=0):
    import jax

    tr = Trainer(CAR_AUTOENCODER, rng=jax.random.PRNGKey(seed))
    tr._ensure_state(np.zeros((4, 18), np.float32))
    return jax.device_get(tr.state.params)


# ------------------------------------------------------------- registry
def test_registry_publish_channels_history_checksum(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    assert reg.versions() == [] and reg.latest() is None
    m1 = reg.publish({"model.h5": params_to_h5_bytes(_params(0))},
                     offsets=[(TOPIC, 0, 10), (TOPIC, 1, 12)],
                     metrics={"loss": 0.5}, step=7)
    assert m1.version == 1 and m1.parent is None and m1.step == 7
    m2 = reg.publish({"model.h5": params_to_h5_bytes(_params(1))})
    assert m2.version == 2
    assert reg.versions() == [1, 2]
    # manifest round-trips offsets/metrics through disk
    got = reg.manifest(1)
    assert got.offsets == [(TOPIC, 0, 10), (TOPIC, 1, 12)]
    assert got.metrics == {"loss": 0.5}
    # channels: promote / rollback are pointer flips with history
    reg.promote(2)
    assert reg.channel("serving") == 2
    reg.rollback(1)
    assert reg.channel("serving") == 1
    events = [e["event"] for e in reg.history()]
    assert events == ["publish", "publish", "promote", "rollback"]
    with pytest.raises(ValueError):
        reg.channel("staging")  # unknown channel names fail loudly
    with pytest.raises(KeyError):
        reg.set_channel("serving", 99)  # uncommitted version
    # the serving cell moves like a leadership topology: rollback is a
    # NEW epoch serving an OLD version
    assert reg.cell.leader == "v0000000001"
    assert reg.cell.epoch == 3  # v2's epoch 2, then rollback bumped
    # artifact reads are checksum-verified
    blob = reg.load_bytes(1, "model.h5")
    assert params_from_h5_bytes(blob) is not None
    path = reg.artifact_path(2, "model.h5")
    with open(path, "r+b") as fh:
        fh.seek(0, os.SEEK_END)
        fh.truncate(fh.tell() - 1)  # torn blob
    with pytest.raises(ValueError, match="checksum"):
        reg.load_bytes(2, "model.h5")


def test_registry_torn_publish_invisible_and_swept(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish({"model.h5": b"x" * 10})
    # simulate a kill between the stage rename and the manifest write:
    # a version dir without a manifest (exactly what the registry.commit
    # faultpoint produces) plus an abandoned stage dir
    torn = reg.version_dir(2)
    os.makedirs(torn)
    with open(os.path.join(torn, "model.h5"), "wb") as fh:
        fh.write(b"torn")
    stage = os.path.join(str(tmp_path), ".stage-v0000000003-999")
    os.makedirs(stage)
    # readers never see either
    assert reg.versions() == [1]
    assert reg.latest() == 1
    with pytest.raises(KeyError):
        reg.manifest(2)
    # recover() sweeps both; committed state untouched
    assert reg.recover() == 2
    assert not os.path.isdir(torn) and not os.path.isdir(stage)
    assert reg.versions() == [1]
    # the torn id is REUSED: ids number commits, not attempts
    m = reg.publish({"model.h5": b"y" * 10})
    assert m.version == 2
    assert reg.versions() == [1, 2]


def test_channel_pointer_to_missing_version_falls_back(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish({"model.h5": b"a"})
    reg.publish({"model.h5": b"b"})
    reg.promote(2)
    # manual surgery / crash between sweep and re-point: the pointer
    # names a version that no longer exists
    shutil.rmtree(reg.version_dir(2))
    assert reg.channel("serving") == 1  # newest intact, not None


def test_registry_prune_keeps_newest_and_channel_targets(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    for i in range(6):
        reg.publish({"model.h5": bytes([i]) * 8})
    reg.set_channel("serving", 2)  # a rolled-back-to old version
    assert reg.prune(keep=2) == 3  # v1, v3, v4 removed
    # newest 2 survive, and the serving target is never pruned
    assert reg.versions() == [2, 5, 6]
    assert reg.channel("serving") == 2
    # ids stay monotonic across a prune: latest() survives every sweep
    assert reg.next_version() == 7
    assert reg.prune(keep=2) == 0  # idempotent at the bound
    events = [e["event"] for e in reg.history()]
    assert events.count("prune") == 1


# ------------------------------------------------- async checkpointer
def test_checkpointer_queue_coalesce_drop_oldest_and_metrics(tmp_path):
    from iotml.obs import metrics as obs_metrics

    reg = ModelRegistry(str(tmp_path))
    ck = AsyncCheckpointer(reg, queue_depth=2, min_interval_s=0.0)
    tr = Trainer(CAR_AUTOENCODER)
    tr._ensure_state(np.zeros((4, 18), np.float32))
    # bounded queue: 3 snapshots into depth 2 evicts the OLDEST
    for i in range(3):
        ck.snapshot(tr.state, [(TOPIC, 0, 10 + i)])
    assert ck.pending() == 2 and ck.dropped == 1
    v1 = ck.write_once()
    v2 = ck.write_once()
    assert ck.write_once() is None
    assert (v1, v2) == (1, 2)
    # the dropped snapshot was the oldest: offsets jump 11 -> 12
    assert reg.manifest(1).offsets == [(TOPIC, 0, 11)]
    assert reg.manifest(2).offsets == [(TOPIC, 0, 12)]
    # auto_promote pointed serving at each commit
    assert reg.channel("serving") == 2
    # cadence throttle: a snapshot arriving inside min_interval_s is
    # coalesced away; force= bypasses (the shutdown edge)
    ck.min_interval_s = 60.0
    ck._last_accept = time.monotonic()
    ck.snapshot(tr.state, [(TOPIC, 0, 13)])
    assert ck.coalesced == 1 and ck.pending() == 0
    ck.snapshot(tr.state, [(TOPIC, 0, 13)], force=True)
    assert ck.pending() == 1 and ck.write_once() == 3
    # phase-labeled checkpoint timings recorded
    with obs_metrics.checkpoint_seconds._lock:
        phases = {dict(k).get("phase")
                  for k in obs_metrics.checkpoint_seconds._series}
    assert {"snapshot", "serialize", "fsync"} <= phases


def test_commit_fn_runs_after_durability(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    ck = AsyncCheckpointer(reg)
    seen = []
    ck.commit_fn = lambda m: seen.append(
        (m.version, reg.versions()[-1]))
    tr = Trainer(CAR_AUTOENCODER)
    tr._ensure_state(np.zeros((4, 18), np.float32))
    ck.snapshot(tr.state, [(TOPIC, 0, 5)])
    ck.write_once()
    # by the time the hook ran, the manifest it names was committed
    assert seen == [(1, 1)]


def test_restore_trainer_full_state_and_weights_only(tmp_path):
    import jax

    reg = ModelRegistry(str(tmp_path))
    assert restore_trainer(Trainer(CAR_AUTOENCODER), reg) is None  # empty
    src = Trainer(CAR_AUTOENCODER)
    src._ensure_state(np.zeros((4, 18), np.float32))
    src.state = src.state.replace(step=np.asarray(41, np.int32))
    ck = AsyncCheckpointer(reg)  # save_opt_state=True
    ck.snapshot(src.state, [(TOPIC, 0, 3)], metrics={"loss": 1.0})
    ck.write_once()
    # full restore: params AND optimizer moments AND step
    dst = Trainer(CAR_AUTOENCODER)
    m = restore_trainer(dst, reg)
    assert m.version == 1 and int(dst.state.step) == 41
    for a, b in zip(jax.tree_util.tree_leaves(dst.state.params),
                    jax.tree_util.tree_leaves(src.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(dst.state.opt_state),
                    jax.tree_util.tree_leaves(src.state.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # weights-only version (scorer-grade checkpoint): opt restarts fresh
    ck2 = AsyncCheckpointer(reg, save_opt_state=False)
    ck2.snapshot(src.state, [(TOPIC, 0, 9)])
    ck2.write_once()
    assert "state.npz" not in reg.manifest(2).artifacts
    dst2 = Trainer(CAR_AUTOENCODER)
    m2 = restore_trainer(dst2, reg)
    assert m2.version == 2
    for a, b in zip(jax.tree_util.tree_leaves(dst2.state.params),
                    jax.tree_util.tree_leaves(src.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_trainer_resumes_lineage_tip_not_serving(tmp_path):
    """A rollback points serving at an OLD version while committed
    offsets keep following the newest manifest — the resumed trainer
    must load the lineage tip, or records in between would be trained
    into no model."""
    reg = ModelRegistry(str(tmp_path))
    src = Trainer(CAR_AUTOENCODER)
    src._ensure_state(np.zeros((4, 18), np.float32))
    ck = AsyncCheckpointer(reg)
    ck.snapshot(src.state, [(TOPIC, 0, 10)])
    ck.write_once()
    ck.snapshot(src.state, [(TOPIC, 0, 20)])
    ck.write_once()
    reg.rollback(1)  # quality gate rejected v2; serving back at v1
    m = restore_trainer(Trainer(CAR_AUTOENCODER), reg)
    assert m.version == 2  # newest committed, NOT the serving channel
    assert m.offsets == [(TOPIC, 0, 20)]


def test_checkpointer_keep_versions_prunes_after_commit(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    ck = AsyncCheckpointer(reg, keep_versions=2)
    tr = Trainer(CAR_AUTOENCODER)
    tr._ensure_state(np.zeros((4, 18), np.float32))
    for i in range(4):
        ck.snapshot(tr.state, [(TOPIC, 0, i)])
        ck.write_once()
    # retention rode every commit: only the newest 2 remain (serving —
    # auto-promoted to the newest — is inside the window)
    assert reg.versions() == [3, 4]
    assert reg.channel("serving") == 4


# ------------------------------------------- trainer crash / resume
def test_trainer_crash_resumes_from_stamped_offsets(tmp_path):
    """Kill-and-remount: the resumed trainer re-consumes from EXACTLY
    the last durable manifest's offsets — no gap (work past the
    checkpoint is re-trained), no double-train (work inside it is
    not)."""
    broker = Broker()
    _seed(broker, 3000)
    group = "crash-train"

    tr = ContinuousTrainer(broker, TOPIC, None,
                           registry=ModelRegistry(str(tmp_path)),
                           group=group, take_batches=5)
    tr.train_round()
    tr.checkpointer.write_once()
    tr.train_round()
    tr.checkpointer.write_once()
    durable = dict(tr.registry.manifest(2).offsets and
                   {(t, p): o for t, p, o in tr.registry.manifest(2).offsets})
    # a third round trains but its checkpoint never lands (the crash):
    # the snapshot sits in the abandoned incarnation's queue
    tr.train_round()
    assert tr.checkpointer.pending() == 1
    advanced = {(t, p): o for t, p, o in tr.consumer.positions()}
    assert any(advanced[k] > durable[k] for k in durable)
    # commit trailed durability: committed == manifest-2 offsets, NOT
    # the crashed round's progress
    for (t, p), off in durable.items():
        assert broker.committed(group, t, p) == off

    # ---- incarnation 2 mounts the same registry root
    reg2 = ModelRegistry(str(tmp_path))
    reg2.recover()
    tr2 = ContinuousTrainer(broker, TOPIC, None, registry=reg2,
                            group=group, take_batches=5)
    assert tr2.restored_version == 2
    assert int(tr2.trainer.state.step) == reg2.manifest(2).step
    resumed = {(t, p): o for t, p, o in tr2.consumer.positions()}
    assert resumed == durable  # the contract, exactly
    # and it trains forward from there
    stats = tr2.train_round()
    v = tr2.checkpointer.write_once()
    assert stats["records"] > 0 and v == 3
    after = {(t, p): o for t, p, o in reg2.manifest(3).offsets}
    assert all(after[k] >= durable[k] for k in durable)


def test_manifest_cursors_beat_backfill_since_ms(tmp_path):
    """PR 5 interaction: a restored manifest's stamped cursors win over
    backfill_since_ms for their partitions (re-reading data the model
    already knows is double-train); a partition the manifest does not
    cover still backfills."""
    b = Broker(store_dir=str(tmp_path / "store"))
    try:
        b.create_topic("t", partitions=2)
        for i in range(50):
            b.produce("t", str(i).encode(), partition=i % 2,
                      timestamp_ms=1000 + i)
        reg = ModelRegistry(str(tmp_path / "reg"))
        src = Trainer(CAR_AUTOENCODER)
        src._ensure_state(np.zeros((4, 18), np.float32))
        ck = AsyncCheckpointer(reg)
        ck.snapshot(src.state, [("t", 0, 17)])  # partition 1 not stamped
        ck.write_once()
        ct = ContinuousTrainer(b, "t", None, registry=reg,
                               group="cold-mlops",
                               backfill_since_ms=1030)
        pos = {p: off for _t, p, off in ct.consumer.positions()}
        assert pos[0] == 17  # manifest beats backfill
        assert pos[1] == b.offset_for_timestamp("t", 1, 1030)
        assert pos[1] > 0  # uncovered partition still backfills
    finally:
        b.close()


def test_manifest_cursor_never_rewinds_committed(tmp_path):
    """Committed offsets ahead of the manifest (a later incarnation
    committed further under a different registry) are never rewound —
    commits stay monotonic across restore."""
    broker = Broker()
    broker.create_topic("t", partitions=1)
    for i in range(40):
        broker.produce("t", str(i).encode(), partition=0)
    broker.commit("fwd", "t", 0, 30)
    reg = ModelRegistry(str(tmp_path))
    src = Trainer(CAR_AUTOENCODER)
    src._ensure_state(np.zeros((4, 18), np.float32))
    ck = AsyncCheckpointer(reg)
    ck.snapshot(src.state, [("t", 0, 12)])  # manifest BEHIND the commit
    ck.write_once()
    ct = ContinuousTrainer(broker, "t", None, registry=reg, group="fwd")
    pos = {p: off for _t, p, off in ct.consumer.positions()}
    assert pos[0] == 30  # resume from committed, not the older manifest


# --------------------------------------- legacy CheckpointManager (R10)
def test_ckptmanager_atomic_save_and_torn_restore(tmp_path):
    from iotml.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    src = Trainer(CAR_AUTOENCODER)
    src._ensure_state(np.zeros((4, 18), np.float32))
    src.state = src.state.replace(step=np.asarray(1, np.int32))
    mgr.save(src.state, step=1, cursors=[(TOPIC, 0, 5)])
    src.state = src.state.replace(step=np.asarray(2, np.int32))
    mgr.save(src.state, step=2, cursors=[(TOPIC, 0, 9)])
    assert mgr.steps() == [1, 2]
    # no staged .tmp dirs survive a completed save
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith(".tmp_step_")]
    # tear the LATEST checkpoint (pre-atomic-save legacy / bit rot):
    # restore() must skip back to the newest intact step, not raise
    step2 = os.path.join(str(tmp_path), "step_0000000002")
    shutil.rmtree(step2)
    os.makedirs(step2)
    with open(os.path.join(step2, "checkpoint"), "wb") as fh:
        fh.write(b"garbage that is not an orbax tree")
    payload = mgr.restore()
    assert payload is not None
    assert int(payload["step"]) == 1
    assert payload["cursors"] == [(TOPIC, 0, 5)]
    assert mgr.skipped_torn == 1
    # an explicitly named torn step still raises: the caller named it
    with pytest.raises(Exception):
        mgr.restore(step=2)


# ------------------------------------------------- watcher + rollout
class _StubScorer:
    def __init__(self):
        self.params = None
        self.model_version = None

    def set_params(self, params, version=None):
        self.params = params
        self.model_version = version


def test_registry_watcher_swaps_and_late_attach(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish({"model.h5": params_to_h5_bytes(_params(0))}).version
    reg.promote(v1)
    s1 = _StubScorer()
    w = RegistryWatcher(reg, scorers=[s1])
    assert w.poll_once() is True
    assert s1.model_version == v1 and s1.params is not None
    assert w.poll_once() is False  # no change, no re-apply
    # a promotion fans out to every attached scorer
    v2 = reg.publish({"model.h5": params_to_h5_bytes(_params(1))}).version
    reg.promote(v2)
    assert w.poll_once() is True and s1.model_version == v2
    # a late joiner immediately receives the CURRENT model
    s2 = _StubScorer()
    w.attach(s2)
    assert s2.model_version == v2 and s2.params is not None
    assert w.swaps == 2


def test_rollout_gate_verdicts():
    gate = RolloutGate(min_records=100, epsilon=0.02)
    base = {"labeled": 500, "f1": 0.8, "auc": 0.9, "precision": 1,
            "recall": 1}
    # not enough evidence on either side -> no verdict
    assert gate.decide(dict(base, labeled=10), base) is None
    assert gate.decide(base, dict(base, labeled=10)) is None
    # no positives seen (undefined AUC) -> wait, never decide on nothing
    assert gate.decide(dict(base, auc=None), base) is None
    # within epsilon -> promote
    assert gate.decide(base, dict(base, f1=0.79, auc=0.89)) == "promote"
    # f1 OR auc regressed past epsilon -> rollback
    assert gate.decide(base, dict(base, f1=0.7)) == "rollback"
    assert gate.decide(base, dict(base, auc=0.8)) == "rollback"


def test_ab_rollout_rolls_back_degraded_candidate(tmp_path):
    broker = Broker()
    n = _seed(broker, 2000, failure_rate=0.05)
    reg = ModelRegistry(str(tmp_path))
    tr = ContinuousTrainer(broker, TOPIC, None, registry=reg,
                           group="ab-train", batch_size=50,
                           take_batches=4, epochs_per_round=3)
    tr.train_round()
    tr.checkpointer.write_once()
    baseline = reg.latest()
    # candidate: baseline weights wrecked with seeded noise
    import jax

    good = params_from_h5_bytes(reg.load_bytes(baseline, "model.h5"))
    noise = np.random.RandomState(7)
    bad = jax.tree_util.tree_map(
        lambda a: np.asarray(a)
        + noise.normal(0, 1.0, np.shape(a)).astype(np.float32), good)
    candidate = reg.publish(
        {"model.h5": params_to_h5_bytes(bad)}).version
    ab = ABRollout(broker, TOPIC, reg, baseline, candidate,
                   gate=RolloutGate(min_records=200, epsilon=0.02),
                   threshold=5.0, deploy_candidate=True, from_start=True,
                   group_prefix="ab-test")
    assert reg.channel("serving") == candidate  # deployed during eval
    for _ in range(64):
        if ab.step(max_rows=5_000) == 0:
            break
    assert ab.decision == "rollback"
    assert reg.channel("serving") == baseline
    # both sides scored the whole stream into their own topics: the
    # comparison artifact is itself on the log
    for v, side in ((baseline, "baseline"), (candidate, "candidate")):
        assert broker.end_offset(f"model-predictions.v{v}", 0) == \
            ab.sides[side].scored == n


def test_scorer_fleet_hot_swaps_every_member():
    """The PR 6 partition-parallel shape: ONE watcher swaps the whole
    fleet between drains when serving moves."""
    import tempfile

    from iotml.cluster import ClusterController, ScorerFleet

    tmp = tempfile.mkdtemp(prefix="iotml_fleet_reg_")
    ctl = ClusterController(brokers=2).start()
    try:
        reg = ModelRegistry(tmp)
        v1 = reg.publish(
            {"model.h5": params_to_h5_bytes(_params(0))}).version
        reg.promote(v1)
        ctl.create_topic(TOPIC, partitions=2)
        ctl.create_topic("preds", partitions=2)
        seed_client = ctl.client()
        gen = FleetGenerator(FleetScenario(num_cars=100))
        gen.publish(seed_client, TOPIC, n_ticks=2, partitions=2)
        fleet = ScorerFleet(
            lambda: ctl.client(), CAR_AUTOENCODER,
            params_from_h5_bytes(reg.load_bytes(v1, "model.h5")),
            n_members=2, in_topic=TOPIC, out_topic="preds",
            group="fleet-swap", registry=reg)
        for _ in range(6):
            fleet.pump_once()
        assert all(m.payload.model_version == v1 for m in fleet.members)
        scored_before = fleet.scored()
        v2 = reg.publish(
            {"model.h5": params_to_h5_bytes(_params(1))}).version
        reg.promote(v2)
        gen.publish(seed_client, TOPIC, n_ticks=2, partitions=2)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            fleet.pump_once()
            if all(m.payload.model_version == v2
                   for m in fleet.members) and \
                    fleet.scored() == 400:
                break
            time.sleep(0.02)
        # every member swapped AND kept scoring: nothing dropped
        assert all(m.payload.model_version == v2 for m in fleet.members)
        assert fleet.scored() == 400 > scored_before
        seed_client.close()
        fleet.stop()
    finally:
        ctl.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def test_live_scorer_follows_registry_serving_channel(tmp_path):
    from iotml.serve.live import LiveScorer

    broker = Broker()
    _seed(broker, 1000)
    reg = ModelRegistry(str(tmp_path))
    v1 = reg.publish({"model.h5": params_to_h5_bytes(_params(0))}).version
    reg.promote(v1)
    svc = LiveScorer(broker, TOPIC, "preds", None, registry=reg,
                     carhealth_topic=None)
    assert svc.wait_for_model(5.0) == "registry:v1"
    assert svc.scorer.score_available() == 1000
    v2 = reg.publish({"model.h5": params_to_h5_bytes(_params(1))}).version
    reg.promote(v2)
    assert svc.maybe_swap() is True
    assert svc.scorer.model_version == v2
    assert svc.maybe_swap() is False
    with pytest.raises(ValueError):
        LiveScorer(broker, TOPIC, "p2", None)  # neither store nor registry


# ------------------------------------------------- platform + config
def test_platform_mounts_registry_and_supervises_units(tmp_path):
    from iotml.cli.up import Platform

    reg0 = ModelRegistry(str(tmp_path))
    reg0.publish({"model.h5": b"x"})
    # leave a torn publish behind: the platform mount must sweep it
    os.makedirs(reg0.version_dir(2))
    plat = Platform(registry_dir=str(tmp_path)).start()
    try:
        assert plat.model_registry.versions() == [1]
        assert not os.path.isdir(plat.model_registry.version_dir(2))
        assert plat.endpoints()["registry"] == str(tmp_path)
        ck = plat.attach_checkpointer(
            AsyncCheckpointer(plat.model_registry))
        sup = plat.supervised()
        names = {u.name for u in sup.units()}
        assert {"registry-watcher", "ckpt-writer"} <= names
        assert ck._external  # the supervisor owns the writer loop
    finally:
        plat.stop()


def test_mlops_config_section_resolves_from_env():
    from iotml.config import load_config

    cfg, _ = load_config([], env={"IOTML_MLOPS_REGISTRY_DIR": "/tmp/r",
                                  "IOTML_MLOPS_QUEUE_DEPTH": "4",
                                  "IOTML_MLOPS_AUTO_PROMOTE": "false"})
    assert cfg.mlops.registry_dir == "/tmp/r"
    assert cfg.mlops.queue_depth == 4
    assert cfg.mlops.auto_promote is False
    with pytest.raises(ValueError):
        load_config([], env={"IOTML_MLOPS_REGISTRY_DIRR": "/tmp/x"})


def test_mlops_cli_registry_inspect(tmp_path, capsys):
    from iotml.mlops.__main__ import main

    reg = ModelRegistry(str(tmp_path))
    v = reg.publish({"model.h5": b"m"}, offsets=[(TOPIC, 0, 4)],
                    metrics={"loss": 0.25}).version
    reg.promote(v)
    assert main(["registry", "--root", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["registry"]["versions"] == [1]
    assert doc["registry"]["serving"] == 1
    assert [e["event"] for e in doc["history"]] == ["publish", "promote"]
