"""MNIST-over-broker ingestion smoke test (reference pair parity)."""

import numpy as np
import pytest

from iotml.cli.mnist_smoke import run as mnist_run
from iotml.data.mnist_stream import (MnistBatches, produce_mnist, synth_mnist)
from iotml.stream.broker import Broker


def test_produce_and_zip_roundtrip():
    images, labels = synth_mnist(100, seed=3)
    broker = Broker()
    assert produce_mnist(broker, images, labels) == 100
    batches = list(MnistBatches(broker, batch_size=32))
    assert [b.n_valid for b in batches] == [32, 32, 32, 4]
    x = np.concatenate([b.x[: b.n_valid] for b in batches])
    y = np.concatenate([b.y[: b.n_valid] for b in batches])
    # byte-exact ingestion: what went in comes out, in order, aligned
    np.testing.assert_array_equal(x, images.astype(np.float32))
    np.testing.assert_array_equal(y, labels)


def test_smoke_cli_streamed_matches_control():
    out = mnist_run(["--n", "600", "--epochs", "3"])
    assert out["ingestion_intact"] is True
    assert out["produced"] == out["streamed_records"] == 600
    s = out["streamed"]
    # the streamed path must actually learn (ingestion didn't scramble data)
    assert s["loss"][-1] < s["loss"][0]
    assert s["accuracy"][-1] > 0.5
    c = out["control"]
    assert c["loss"][-1] < c["loss"][0]
