"""Model zoo: shapes, regularizer semantics, h5 import parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import requires_reference, REFERENCE_ROOT
from iotml.models.autoencoder import (CAR_AUTOENCODER, CREDITCARD_AUTOENCODER,
                                      DenseAutoencoder, reconstruction_error)
from iotml.models.lstm import LSTMSeq2Seq
from iotml.models.mnist import MNISTClassifier, MNISTBaseline


def _init(model, shape):
    x = jnp.zeros(shape, jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    return params, x


def test_autoencoder_shapes_and_param_counts():
    params, x = _init(CAR_AUTOENCODER, (4, 18))
    out = CAR_AUTOENCODER.apply({"params": params}, x)
    assert out.shape == (4, 18)
    # layer dims 18→14→7→7→18 (cardata-v3.py:176-194)
    assert params["encoder0"]["kernel"].shape == (18, 14)
    assert params["encoder1"]["kernel"].shape == (14, 7)
    assert params["decoder0"]["kernel"].shape == (7, 7)
    assert params["decoder1"]["kernel"].shape == (7, 18)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # keras summary for this model: 18*14+14 + 14*7+7 + 7*7+7 + 7*18+18 = 571
    assert n_params == 571


def test_activity_penalty_matches_keras_semantics():
    params, _ = _init(CAR_AUTOENCODER, (4, 18))
    x = jnp.ones((4, 18))
    out, pen = CAR_AUTOENCODER.apply({"params": params}, x, with_penalty=True)
    # keras: l1 * sum(|tanh(xW+b)|) / batch
    h = np.tanh(x @ params["encoder0"]["kernel"] + params["encoder0"]["bias"])
    expect = 1e-7 * np.sum(np.abs(h)) / 4
    assert float(pen) == pytest.approx(float(expect), rel=1e-5)


def test_autoencoder_encode_latent():
    params, _ = _init(CAR_AUTOENCODER, (4, 18))
    x = jnp.ones((4, 18))
    from iotml.models.autoencoder import DenseAutoencoder

    code = CAR_AUTOENCODER.apply({"params": params}, x,
                                 method=DenseAutoencoder.encode)
    assert code.shape == (4, 7)
    # encode must agree with the first two layers of __call__'s math
    h = np.tanh(x @ params["encoder0"]["kernel"] + params["encoder0"]["bias"])
    expect = np.maximum(h @ params["encoder1"]["kernel"]
                        + params["encoder1"]["bias"], 0.0)
    np.testing.assert_allclose(np.asarray(code), expect, rtol=1e-5, atol=1e-6)


def test_creditcard_variant_is_30_dim():
    params, x = _init(CREDITCARD_AUTOENCODER, (2, 30))
    out = CREDITCARD_AUTOENCODER.apply({"params": params}, x)
    assert out.shape == (2, 30)


def test_reconstruction_error_per_row():
    model = DenseAutoencoder(input_dim=6)
    params, _ = _init(model, (3, 6))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 6)), jnp.float32)
    err = reconstruction_error(model, params, x)
    assert err.shape == (3,)
    assert np.all(np.asarray(err) >= 0)


def test_lstm_seq2seq_shapes():
    model = LSTMSeq2Seq(features=18, look_back=1)
    x = jnp.zeros((2, 1, 18))
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (2, 1, 18)
    # longer windows compile too (host windower may use T > 1)
    model4 = LSTMSeq2Seq(features=18, look_back=4)
    x4 = jnp.zeros((2, 4, 18))
    p4 = model4.init(jax.random.PRNGKey(0), x4)["params"]
    assert model4.apply({"params": p4}, x4).shape == (2, 4, 18)


def test_mnist_models():
    for cls in (MNISTClassifier, MNISTBaseline):
        m = cls()
        x = jnp.zeros((2, 28, 28))
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        assert m.apply({"params": params}, x).shape == (2, 10)


@requires_reference
def test_h5_import_reference_checkpoint():
    """Load the reference's trained 30-dim autoencoder and score with it."""
    from iotml.models.h5_import import autoencoder_params_from_h5

    path = f"{REFERENCE_ROOT}/models/autoencoder_sensor_anomaly_detection.h5"
    params = autoencoder_params_from_h5(path)
    assert params["encoder0"]["kernel"].shape == (30, 14)
    assert params["decoder1"]["kernel"].shape == (7, 30)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(5, 30)), jnp.float32)
    out = CREDITCARD_AUTOENCODER.apply({"params": jax.tree.map(jnp.asarray, params)}, x)
    assert out.shape == (5, 30)
    assert np.all(np.isfinite(np.asarray(out)))


@requires_reference
def test_h5_import_numeric_parity_with_numpy_forward():
    """VERDICT r1: pin the h5→flax mapping NUMERICALLY, not just by shape.
    A numpy forward pass computed directly from the raw h5 tensors (in
    Keras layer order, tanh/relu/tanh/relu) must match flax.apply with the
    imported params — a transposed kernel or swapped layer would diverge."""
    import h5py

    from iotml.models.h5_import import autoencoder_params_from_h5

    path = f"{REFERENCE_ROOT}/models/autoencoder_sensor_anomaly_detection.h5"

    # raw tensors straight out of the file, no importer involved
    raw = []
    with h5py.File(path, "r") as f:
        for name in ("dense", "dense_1", "dense_2", "dense_3"):
            g = f["model_weights"][name][name]
            raw.append((np.asarray(g["kernel:0"]), np.asarray(g["bias:0"])))

    x = np.random.default_rng(7).normal(size=(16, 30)).astype(np.float32)
    h = np.tanh(x @ raw[0][0] + raw[0][1])
    h = np.maximum(h @ raw[1][0] + raw[1][1], 0.0)
    h = np.tanh(h @ raw[2][0] + raw[2][1])
    expected = np.maximum(h @ raw[3][0] + raw[3][1], 0.0)

    params = jax.tree.map(jnp.asarray, autoencoder_params_from_h5(path))
    got = np.asarray(CREDITCARD_AUTOENCODER.apply({"params": params},
                                                  jnp.asarray(x)))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_h5_export_import_roundtrip(tmp_path):
    """VERDICT r1: export repo-trained params as a Keras h5 and read them
    back — the tree must round-trip exactly."""
    from iotml.models.h5_export import autoencoder_params_to_h5
    from iotml.models.h5_import import autoencoder_params_from_h5

    params = CAR_AUTOENCODER.init(
        jax.random.PRNGKey(3), jnp.zeros((1, 18)))["params"]
    out = str(tmp_path / "exported.h5")
    autoencoder_params_to_h5(jax.tree.map(np.asarray, params), out)
    back = autoencoder_params_from_h5(out, expect_dims=(18, 14))
    for layer in ("encoder0", "encoder1", "decoder0", "decoder1"):
        for leaf in ("kernel", "bias"):
            np.testing.assert_array_equal(back[layer][leaf],
                                          np.asarray(params[layer][leaf]))


@requires_reference
def test_h5_export_layout_matches_reference_checkpoint(tmp_path):
    """The exported file mirrors the reference checkpoint's HDF5 layout
    attribute-for-attribute, so a Keras-side `load_model` finds everything
    it walks: model_config/training_config at root, layer_names and
    per-layer weight_names, nested <layer>/<layer>/{kernel:0,bias:0}."""
    import h5py
    import json

    from iotml.models.h5_export import autoencoder_params_to_h5
    from iotml.models.h5_import import autoencoder_params_from_h5

    ref = f"{REFERENCE_ROOT}/models/autoencoder_sensor_anomaly_detection.h5"
    params = autoencoder_params_from_h5(ref)  # 30-dim, so dims line up
    out = str(tmp_path / "exported.h5")
    autoencoder_params_to_h5(params, out)

    with h5py.File(ref, "r") as fr, h5py.File(out, "r") as fo:
        assert set(fr.attrs) == set(fo.attrs)
        mc_ref = json.loads(fr.attrs["model_config"])
        mc_out = json.loads(fo.attrs["model_config"])
        assert [l["class_name"] for l in mc_ref["config"]["layers"]] == \
            [l["class_name"] for l in mc_out["config"]["layers"]]
        for lr, lo in zip(mc_ref["config"]["layers"][1:],
                          mc_out["config"]["layers"][1:]):
            assert lr["config"]["units"] == lo["config"]["units"]
            assert lr["config"]["activation"] == lo["config"]["activation"]
        assert list(fr["model_weights"].attrs["layer_names"]) == \
            list(fo["model_weights"].attrs["layer_names"])
        for name in ("dense", "dense_1", "dense_2", "dense_3"):
            gr, go = fr["model_weights"][name], fo["model_weights"][name]
            assert list(gr.attrs["weight_names"]) == \
                list(go.attrs["weight_names"])
            for leaf in ("kernel:0", "bias:0"):
                assert gr[name][leaf].shape == go[name][leaf].shape
                assert gr[name][leaf].dtype == go[name][leaf].dtype
