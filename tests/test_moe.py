"""MoE SensorFormer + expert parallelism: the expert-sharded all_to_all
path must match the single-device dense dispatch, and routing must respect
capacity with static shapes throughout."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from iotml.models.moe import MoEFFN, MoESensorFormer
from iotml.parallel.expert_parallel import (expert_param_specs,
                                            make_ep_train_step)
from iotml.parallel.mesh import make_mesh
from jax.sharding import PartitionSpec as P


def _x(B=4, T=16, F=18, seed=0):
    return np.random.default_rng(seed).normal(size=(B, T, F)).astype(np.float32)


def test_moe_ffn_shapes_and_aux():
    ffn = MoEFFN(d_model=16, num_experts=4)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)),
                    jnp.float32)
    params = ffn.init(jax.random.PRNGKey(0), x)["params"]
    out, aux = ffn.apply({"params": params}, x)
    assert out.shape == x.shape
    # perfectly balanced routing gives aux = 1.0; any routing >= 1.0-ish
    assert 0.5 < float(aux) < 4.0


def test_moe_capacity_drops_are_residual_passthrough():
    # capacity_factor tiny -> most tokens dropped -> their FFN output is 0
    ffn = MoEFFN(d_model=8, num_experts=2, capacity_factor=0.01)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(64, 8)), jnp.float32)
    params = ffn.init(jax.random.PRNGKey(0), x)["params"]
    out, _ = ffn.apply({"params": params}, x)
    # C = max(1, 0.01*64/2) = 1 slot per expert -> at most 2 nonzero rows
    nonzero_rows = int(jnp.sum(jnp.any(jnp.abs(out) > 0, axis=-1)))
    assert nonzero_rows <= 2


def test_moe_sensorformer_forward():
    m = MoESensorFormer(features=18, d_model=32, num_heads=2, num_layers=2,
                        num_experts=4)
    x = jnp.asarray(_x())
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    pred, aux = m.apply({"params": params}, x)
    assert pred.shape == x.shape
    assert np.isfinite(float(aux))


def test_expert_param_specs_target_only_expert_weights():
    m = MoESensorFormer(features=6, d_model=16, num_heads=2, num_layers=1,
                        num_experts=4)
    params = m.init(jax.random.PRNGKey(0),
                    jnp.zeros((2, 8, 6), jnp.float32))["params"]
    specs = expert_param_specs(params)
    assert specs["block0"]["moe"]["w1"] == P("expert")
    assert specs["block0"]["moe"]["router"]["kernel"] == P()
    assert specs["embed"]["kernel"] == P()


def test_ep_matches_dense_dispatch_when_no_drops():
    """With capacity >= all tokens, every token is routed; the expert-
    parallel all_to_all path must reproduce the dense einsum path exactly."""
    E = 4
    mesh = make_mesh((2, 2), ("data", "expert"), devices=jax.devices()[:4])
    # capacity_factor = E guarantees C >= N_local, so no token ever drops
    model = MoESensorFormer(features=6, d_model=16, num_heads=2, num_layers=1,
                            num_experts=E, capacity_factor=float(E))
    x = _x(B=8, T=8, F=6, seed=3)
    dense_params = model.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    pred_dense, _ = model.apply({"params": dense_params}, jnp.asarray(x))

    init, step, put_x = make_ep_train_step(model, optax.sgd(0.0), mesh)
    state = init(jax.random.PRNGKey(0), x)

    # run the sharded forward via the loss's mse output against the oracle
    _, metrics = step(state, put_x(x))
    want = float(jnp.mean(jnp.square(pred_dense[:, :-1] - x[:, 1:])))
    np.testing.assert_allclose(float(metrics["mse"]), want, rtol=1e-4)


def test_ep_train_step_learns():
    mesh = make_mesh((2, 4), ("data", "expert"))
    model = MoESensorFormer(features=6, d_model=16, num_heads=2, num_layers=1,
                            num_experts=8, capacity_factor=2.0)
    init, step, put_x = make_ep_train_step(model, optax.adam(1e-2), mesh)
    x = _x(B=8, T=8, F=6, seed=4)
    state = init(jax.random.PRNGKey(1), x)
    losses = []
    for _ in range(5):
        state, m = step(state, put_x(x))
        losses.append(float(m["mse"]))
    assert losses[-1] < losses[0]
    # expert weights actually sharded: local leading dim = E/ep = 8/4 = 2
    w1 = state.params["block0"]["moe"]["w1"]
    assert w1.sharding.shard_shape(w1.shape)[0] == 2
