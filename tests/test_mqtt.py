"""MQTT layer: topic matching, broker semantics, wire protocol, bridge,
scenario runner — the reference's L1/L2 (HiveMQ + device simulator)."""

import json

import pytest

from iotml.mqtt.topic_tree import TopicTree, split_share, topic_matches
from iotml.mqtt.broker import MqttBroker, QueueClient
from iotml.mqtt.bridge import KafkaBridge, TopicMapping
from iotml.mqtt.scenario import (EVALUATION_SCENARIO, ScenarioRunner,
                                 expand_pattern, parse_rate, parse_scenario)
from iotml.mqtt.wire import MqttClient, MqttServer
from iotml.stream.broker import Broker


# ------------------------------------------------------------- matching
@pytest.mark.parametrize("filt,topic,expect", [
    ("vehicles/sensor/data/#", "vehicles/sensor/data/car-1", True),
    ("vehicles/sensor/data/#", "vehicles/sensor/data/a/b/c", True),
    ("vehicles/sensor/data/#", "vehicles/sensor/data", True),  # parent
    ("vehicles/sensor/data/#", "vehicles/sensor/other/car-1", False),
    ("vehicles/+/data/+", "vehicles/sensor/data/car-1", True),
    ("vehicles/+/data/+", "vehicles/sensor/data/a/b", False),
    ("+", "vehicles", True),
    ("+", "vehicles/sensor", False),
    ("#", "anything/at/all", True),
    ("#", "$SYS/broker/load", False),      # $-topic shielded from root #
    ("+/monitor", "$SYS/monitor", False),  # ... and from root +
    ("$SYS/#", "$SYS/broker/load", True),  # explicit $ filter matches
    ("sport/tennis/player1/#", "sport/tennis/player1/ranking", True),
])
def test_topic_matches(filt, topic, expect):
    assert topic_matches(filt, topic) is expect


def test_split_share():
    assert split_share("$share/consumers/vehicles/#") == \
        ("consumers", "vehicles/#")
    assert split_share("vehicles/#") == (None, "vehicles/#")
    with pytest.raises(ValueError):
        split_share("$share/nogroup")


def test_tree_wildcards_and_overlap():
    tree = TopicTree()
    tree.subscribe("a", "vehicles/sensor/data/#")
    tree.subscribe("b", "vehicles/+/data/car-1")
    tree.subscribe("c", "vehicles/sensor/data/car-1")
    got = dict(tree.receivers("vehicles/sensor/data/car-1"))
    assert set(got) == {"a", "b", "c"}
    # a client matching via two overlapping filters is delivered once
    tree.subscribe("a", "vehicles/#")
    assert [cid for cid, _ in
            tree.receivers("vehicles/sensor/data/car-2")].count("a") == 1


def test_shared_subscription_round_robin():
    """$share/consumers/... delivers each publish to exactly one member
    (reference scenario.xml:33-35 — six shared consumers)."""
    tree = TopicTree()
    for i in range(3):
        tree.subscribe(f"consumer-{i}", "$share/consumers/vehicles/#")
    hits = []
    for _ in range(9):
        got = tree.receivers("vehicles/sensor/data/car-7")
        assert len(got) == 1
        hits.append(got[0][0])
    assert set(hits) == {"consumer-0", "consumer-1", "consumer-2"}
    assert hits.count("consumer-0") == 3  # balanced


# --------------------------------------------------------------- broker
def test_broker_publish_subscribe_retained():
    b = MqttBroker()
    c1 = QueueClient(b, "sub-1")
    c1.subscribe("tele/+/status")
    b.publish("tele/dev1/status", b"up", retain=True)
    assert c1.messages[-1][:2] == ("tele/dev1/status", b"up")
    # late subscriber receives the retained message, flagged retain=True
    c2 = QueueClient(b, "sub-2")
    c2.subscribe("tele/#")
    assert c2.messages[-1] == ("tele/dev1/status", b"up", 0, True)
    # empty payload clears the retained message
    b.publish("tele/dev1/status", b"", retain=True)
    c3 = QueueClient(b, "sub-3")
    c3.subscribe("tele/#")
    assert c3.messages == []


def test_broker_session_takeover_and_disconnect():
    b = MqttBroker()
    c1 = QueueClient(b, "dev")
    c1.subscribe("t/#")
    b.publish("t/x", b"1")
    assert len(c1.messages) == 1
    QueueClient(b, "dev")  # takeover: clean session drops old subs
    b.publish("t/x", b"2")
    assert len(c1.messages) == 1
    b.disconnect("dev")
    assert b.session_count() == 0


def test_publish_rejects_wildcards():
    b = MqttBroker()
    with pytest.raises(ValueError):
        b.publish("vehicles/#", b"x")


# ----------------------------------------------------------------- wire
def test_wire_end_to_end_qos0_qos1():
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        sub = MqttClient("127.0.0.1", srv.port, "sub",
                         on_message=lambda t, p: got.append((t, p)))
        sub.subscribe("vehicles/sensor/data/#", qos=1)
        pub = MqttClient("127.0.0.1", srv.port, "pub")
        pub.publish("vehicles/sensor/data/car-1", b"hello", qos=0)
        pub.publish("vehicles/sensor/data/car-2", b"acked", qos=1)  # waits for PUBACK
        import time
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert sorted(got) == [("vehicles/sensor/data/car-1", b"hello"),
                               ("vehicles/sensor/data/car-2", b"acked")]
        pub.disconnect()
        sub.disconnect()


def test_wire_mqtt5_client():
    """Protocol-level-5 packets (with properties byte) round-trip."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        sub = MqttClient("127.0.0.1", srv.port, "sub5", protocol_level=5,
                         on_message=lambda t, p: got.append((t, p)))
        sub.subscribe("a/b", qos=1)
        pub = MqttClient("127.0.0.1", srv.port, "pub5", protocol_level=5)
        pub.publish("a/b", b"v5", qos=1)
        import time
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("a/b", b"v5")]
        pub.disconnect()
        sub.disconnect()


# --------------------------------------------------------------- bridge
def test_bridge_topic_mapping():
    """vehicles/sensor/data/# → stream topic sensor-data, key = MQTT topic
    (reference kafka-config.yaml:20-29)."""
    mqtt = MqttBroker()
    stream = Broker()
    bridge = KafkaBridge(mqtt, stream, partitions=10)
    pub = QueueClient(mqtt, "car")
    pub.publish("vehicles/sensor/data/electric-vehicle-00001", b"payload-1")
    pub.publish("vehicles/other/evt", b"not-mapped")
    assert bridge.forwarded() == 1
    total = sum(len(stream.fetch("sensor-data", p, 0))
                for p in range(10))
    assert total == 1
    msgs = [m for p in range(10) for m in stream.fetch("sensor-data", p, 0)]
    assert msgs[0].value == b"payload-1"
    assert msgs[0].key == b"vehicles/sensor/data/electric-vehicle-00001"
    # per-instance accounting: a second bridge on fresh brokers starts at 0
    bridge2 = KafkaBridge(MqttBroker(), Broker(), partitions=1)
    assert bridge2.forwarded() == 0
    assert bridge.forwarded() == 1


# ------------------------------------------------------------- scenario
def test_parse_helpers():
    assert parse_rate("1/10s") == pytest.approx(0.1)
    assert parse_rate("5/s") == pytest.approx(5.0)
    assert expand_pattern("electric-vehicle-[0-9]{5}", 7) == \
        "electric-vehicle-00007"


def test_parse_reference_shaped_xml():
    xml = """<?xml version="1.0"?>
    <scenario>
      <brokers><broker id="b1"><address>h</address><port>1883</port></broker></brokers>
      <clientGroups>
        <clientGroup id="cg1"><clientIdPattern>car-[0-9]{3}</clientIdPattern>
          <count>10</count><mqttVersion>5</mqttVersion></clientGroup>
      </clientGroups>
      <topicGroups>
        <topicGroup id="tg1"><topicNamePattern>vehicles/sensor/data/car-[0-9]{3}</topicNamePattern>
          <count>10</count></topicGroup>
      </topicGroups>
      <subscriptions>
        <subscription id="s1"><topicFilter>$share/consumers/vehicles/sensor/data/#</topicFilter></subscription>
      </subscriptions>
      <stages>
        <stage id="st1">
          <lifeCycle id="publ" clientGroup="cg1">
            <rampUp duration="20s"/>
            <publish topicGroup="tg1" qos="0" count="3" rate="1/10s"/>
            <disconnect/>
          </lifeCycle>
        </stage>
      </stages>
    </scenario>"""
    sc = parse_scenario(xml)
    assert sc.client_groups["cg1"].count == 10
    assert sc.stages[0].lifecycles[0].publish.rate_per_s == pytest.approx(0.1)
    assert sc.stages[0].lifecycles[0].ramp_up_s == 20.0
    assert sc.subscriptions[0].topic_filter.startswith("$share/")


def test_scenario_run_to_training_batches():
    """Full ingestion slice: scenario agents → MQTT → bridge → sensor-data
    → KSQL-equivalent JSON→Avro → consumable training batches."""
    from iotml.data.dataset import SensorBatches
    from iotml.stream.consumer import StreamConsumer
    from iotml.streamproc.tasks import JsonToAvro

    mqtt = MqttBroker()
    stream = Broker()
    KafkaBridge(mqtt, stream, partitions=1)
    runner = ScenarioRunner(EVALUATION_SCENARIO, mqtt)
    summary = runner.run()
    assert summary["published"] == 25 * 40
    # shared consumer group saw every publish exactly once
    assert summary["consumer-sub-1-shared"] == 25 * 40

    task = JsonToAvro(stream, src="sensor-data", dst="SENSOR_DATA_S_AVRO")
    assert task.process_available() == 1000
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="test-mqtt-slice")
    batches = list(SensorBatches(consumer, batch_size=100))
    assert sum(b.n_valid for b in batches) == 1000
    assert batches[0].x.shape == (100, 18)


def test_wire_session_takeover_survives_old_teardown():
    """A reconnect with the same client id must survive the stale
    connection's teardown (identity-checked disconnect)."""
    import time
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        c_old = MqttClient("127.0.0.1", srv.port, "dev")
        c_new = MqttClient("127.0.0.1", srv.port, "dev",
                           on_message=lambda t, p: got.append((t, p)))
        c_new.subscribe("t/#")
        c_old.disconnect()  # stale teardown must not kill c_new's session
        time.sleep(0.1)
        pub = MqttClient("127.0.0.1", srv.port, "pub")
        pub.publish("t/x", b"alive", qos=1)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("t/x", b"alive")]
        pub.disconnect()
        c_new.disconnect()


def test_wire_mqtt5_large_properties_varint():
    """Properties blocks >=128 bytes use a multi-byte varint length; the
    parser must skip them exactly (spec 2.2.2)."""
    import struct
    import time
    from iotml.mqtt.wire import (PUBLISH, _mqtt_str, encode_varlen, packet)
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        sub = MqttClient("127.0.0.1", srv.port, "sub",
                         on_message=lambda t, p: got.append((t, p)))
        sub.subscribe("big/props")
        pub = MqttClient("127.0.0.1", srv.port, "pub5", protocol_level=5)
        # hand-build a level-5 PUBLISH with a 200-byte properties block
        # (user property 0x26)
        props = bytes([0x26]) + _mqtt_str("k" * 95) + _mqtt_str("v" * 98)
        assert len(props) >= 128
        body = _mqtt_str("big/props") + encode_varlen(len(props)) + props \
            + b"payload"
        pub._sock.sendall(packet(PUBLISH, 0, body))
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("big/props", b"payload")]
        pub.disconnect()
        sub.disconnect()


def test_scenario_tcp_transport_qos0_quiesce():
    """qos-0 over real TCP: ping-barrier quiesce makes counts exact."""
    import dataclasses as dc
    from iotml.mqtt.scenario import (EVALUATION_SCENARIO, PublishSpec,
                                     LifeCycle, Stage)
    sc = dc.replace(
        EVALUATION_SCENARIO,
        client_groups={"cg1": dc.replace(
            EVALUATION_SCENARIO.client_groups["cg1"], count=5)},
        stages=[Stage("publish", [LifeCycle(
            "publ", "cg1", connect=True,
            publish=PublishSpec("tg1", qos=0, count=4, rate_per_s=1e9),
            disconnect=True)])])
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        runner = ScenarioRunner(sc, broker, transport="tcp", port=srv.port)
        summary = runner.run()
    assert summary["published"] == 20
    assert summary["consumer-sub-1-shared"] == 20


def test_topic_group_wildcard_subscription_runs():
    """sub via <topicGroup> + <wildCard>true</wildCard> — the reference's
    scenario.xml sub-1 shape — must derive a *valid* filter
    ('vehicles/sensor/data/#') and count every publish; regression for the
    invalid 'electric-vehicle-#' partial-level filter."""
    xml = """<?xml version="1.0"?>
    <scenario>
      <clientGroups>
        <clientGroup id="cg1"><clientIdPattern>car-[0-9]{2}</clientIdPattern>
          <count>5</count></clientGroup>
      </clientGroups>
      <topicGroups>
        <topicGroup id="tg1"><topicNamePattern>vehicles/sensor/data/car-[0-9]{2}</topicNamePattern>
          <count>5</count></topicGroup>
      </topicGroups>
      <subscriptions>
        <subscription id="s1"><topicGroup>tg1</topicGroup><wildCard>true</wildCard></subscription>
        <subscription id="s2"><topicGroup>tg1</topicGroup><wildCard>false</wildCard></subscription>
      </subscriptions>
      <stages>
        <stage id="st1">
          <lifeCycle id="publ" clientGroup="cg1">
            <publish topicGroup="tg1" qos="0" count="4"/>
            <disconnect/>
          </lifeCycle>
        </stage>
      </stages>
    </scenario>"""
    sc = parse_scenario(xml)
    runner = ScenarioRunner(sc, MqttBroker())
    summary = runner.run()
    assert summary["published"] == 20
    assert summary["consumer-s1"] == 20  # wildcard collapse
    assert summary["consumer-s2"] == 20  # per-topic expansion


def test_wire_subscribe_rejected_raises():
    """A server-side 0x80 SUBACK code must surface as an error, not silent
    no-delivery."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        c = MqttClient("127.0.0.1", srv.port, "c1")
        try:
            with pytest.raises(ValueError, match="rejected"):
                c.subscribe("a/#/b")  # '#' not final ⇒ invalid filter
            c.subscribe("a/#")  # valid one still works after the rejection
        finally:
            c.disconnect()


def test_wire_client_clears_connect_timeout():
    """The 10s connect timeout must not persist onto the reader socket —
    an idle subscriber's reader thread would die on recv timeout."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        c = MqttClient("127.0.0.1", srv.port, "idle")
        try:
            assert c._sock.gettimeout() is None
            assert c._reader.is_alive()
        finally:
            c.disconnect()


def test_wire_server_survives_protocol_violation():
    """A wildcard PUBLISH topic is a protocol error: the offender is
    dropped without a stderr traceback and the server keeps serving."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        bad = MqttClient("127.0.0.1", srv.port, "bad")
        bad.publish("a/+/b", b"x", qos=0)  # server drops the connection
        import time
        deadline = time.time() + 5
        while bad._reader.is_alive() and time.time() < deadline:
            time.sleep(0.01)
        assert not bad._reader.is_alive()
        # a fresh client still gets full service
        got = []
        ok = MqttClient("127.0.0.1", srv.port, "ok",
                        on_message=lambda t, p: got.append(p))
        ok.subscribe("a/#")
        ok.publish("a/b", b"fine", qos=1)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [b"fine"]
        ok.disconnect()


def test_scenario_topic_group_smaller_than_client_group():
    """Agents must wrap onto the topic group's declared topics (i % count),
    not invent undeclared ones that bypass the group's subscribers."""
    xml = """<?xml version="1.0"?>
    <scenario>
      <clientGroups>
        <clientGroup id="cg1"><clientIdPattern>car-[0-9]{2}</clientIdPattern>
          <count>10</count></clientGroup>
      </clientGroups>
      <topicGroups>
        <topicGroup id="tg1"><topicNamePattern>v/s/d/car-[0-9]{2}</topicNamePattern>
          <count>5</count></topicGroup>
      </topicGroups>
      <subscriptions>
        <subscription id="s2"><topicGroup>tg1</topicGroup><wildCard>false</wildCard></subscription>
      </subscriptions>
      <stages>
        <stage id="st1">
          <lifeCycle id="publ" clientGroup="cg1">
            <publish topicGroup="tg1" qos="0" count="2"/>
            <disconnect/>
          </lifeCycle>
        </stage>
      </stages>
    </scenario>"""
    sc = parse_scenario(xml)
    summary = ScenarioRunner(sc, MqttBroker()).run()
    assert summary["published"] == 20
    assert summary["consumer-s2"] == 20  # nothing bypasses the group


def test_persistent_session_queues_qos1_while_offline():
    """HiveMQ semantics: a persistent session's QoS≥1 messages are queued
    while it is offline and delivered on reconnect; QoS 0 is not queued;
    a clean reconnect discards the queue."""
    from iotml.mqtt.broker import MqttBroker, QueueClient

    broker = MqttBroker()
    c = QueueClient(broker, "car-1", clean_start=False)
    c.subscribe("vehicles/sensor/data/#", qos=1)
    broker.publish("vehicles/sensor/data/car-1", b"live", qos=1)
    assert [m[1] for m in c.messages] == [b"live"]

    broker.disconnect("car-1")
    broker.publish("vehicles/sensor/data/car-1", b"offline-1", qos=1)
    broker.publish("vehicles/sensor/data/car-1", b"offline-q0", qos=0)
    broker.publish("vehicles/sensor/data/car-1", b"offline-2", qos=1)

    c2 = QueueClient(broker, "car-1", clean_start=False)
    # queued QoS1 messages arrive on reconnect, in order; QoS0 was dropped
    assert [m[1] for m in c2.messages] == [b"offline-1", b"offline-2"]
    # subscription survived too: new publishes flow
    broker.publish("vehicles/sensor/data/car-1", b"after", qos=1)
    assert c2.messages[-1][1] == b"after"

    # clean reconnect discards both the queue and the subscriptions
    broker.disconnect("car-1")
    broker.publish("vehicles/sensor/data/car-1", b"lost", qos=1)
    c3 = QueueClient(broker, "car-1", clean_start=True)
    assert c3.messages == []
    broker.publish("vehicles/sensor/data/car-1", b"unrouted", qos=1)
    assert c3.messages == []


def test_offline_queue_bounded_drop_oldest():
    from iotml.mqtt.broker import MqttBroker, QueueClient

    broker = MqttBroker(offline_queue_limit=3)
    c = QueueClient(broker, "c", clean_start=False)
    c.subscribe("t", qos=1)
    broker.disconnect("c")
    for i in range(5):
        broker.publish("t", f"m{i}".encode(), qos=1)
    c2 = QueueClient(broker, "c", clean_start=False)
    assert [m[1] for m in c2.messages] == [b"m2", b"m3", b"m4"]


def test_offline_session_expiry_drops_queue_and_subscriptions():
    import time as _time
    from unittest import mock

    from iotml.mqtt.broker import MqttBroker, QueueClient

    broker = MqttBroker(offline_session_expiry_s=10.0)
    c = QueueClient(broker, "gone", clean_start=False)
    c.subscribe("t", qos=1)
    broker.disconnect("gone")
    broker.publish("t", b"queued", qos=1)
    assert broker._offline  # queued while within expiry

    with mock.patch("iotml.mqtt.broker.time") as m:
        # session deadlines live in the monotonic clock domain (a wall
        # clock step must not expire or extend sessions)
        m.monotonic.return_value = _time.monotonic() + 11.0
        # any session operation sweeps expired offline state
        QueueClient(broker, "other", clean_start=True)
    assert not broker._offline
    # the expired session's subscription is gone: publish routes nowhere
    assert broker.publish("t", b"after-expiry", qos=1) == 0
    c2 = QueueClient(broker, "gone", clean_start=False)
    assert c2.messages == []


def test_queued_publish_not_counted_as_dropped():
    from iotml.mqtt.broker import MqttBroker, QueueClient

    broker = MqttBroker()
    dropped0 = broker._m_dropped.value()
    queued0 = broker._m_queued.value()
    c = QueueClient(broker, "c", clean_start=False)
    c.subscribe("t", qos=1)
    broker.disconnect("c")
    broker.publish("t", b"x", qos=1)
    assert broker._m_dropped.value() == dropped0
    assert broker._m_queued.value() == queued0 + 1


def test_wire_reconnect_delivers_queue_after_connack():
    """Persistent session over real TCP: CONNACK must precede the queued
    PUBLISHes, or the client's handshake parser rejects the stream."""
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.wire import MqttClient, MqttServer

    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        c = MqttClient("127.0.0.1", srv.port, "car-9", clean=False,
                       on_message=lambda t, p: got.append(p))
        c.subscribe("t", qos=1)
        c.disconnect()
        # the server handler tears the session down asynchronously; publish
        # only once the broker has seen the disconnect (else the message
        # races the closed socket instead of the offline queue)
        deadline = __import__("time").time() + 5
        while broker.session_count() and __import__("time").time() < deadline:
            __import__("time").sleep(0.02)
        assert broker.session_count() == 0
        broker.publish("t", b"while-away-1", qos=1)
        broker.publish("t", b"while-away-2", qos=1)
        c2 = MqttClient("127.0.0.1", srv.port, "car-9", clean=False,
                        on_message=lambda t, p: got.append(p))
        deadline = __import__("time").time() + 5
        while len(got) < 2 and __import__("time").time() < deadline:
            __import__("time").sleep(0.05)
        assert got == [b"while-away-1", b"while-away-2"]
        c2.disconnect()


def test_takeover_mid_handshake_moves_backlog_to_new_session():
    """Reconnect storm: a second CONNECT for the same client id before the
    first connection drained its backlog must inherit the queue; the
    superseded connection's drain must deliver nothing."""
    from iotml.mqtt.broker import MqttBroker

    broker = MqttBroker()
    got_a, got_b = [], []
    sa = broker.connect("car", lambda t, p, q, r: got_a.append(p),
                        clean_start=False)
    broker.deliver_pending(sa)
    broker.subscribe("car", "t", qos=1)
    broker.disconnect("car")
    broker.publish("t", b"queued-1", qos=1)
    broker.publish("t", b"queued-2", qos=1)

    sa2 = broker.connect("car", lambda t, p, q, r: got_a.append(p),
                         clean_start=False)       # connection A (stalls)
    sb = broker.connect("car", lambda t, p, q, r: got_b.append(p),
                        clean_start=False)        # takeover: connection B
    assert broker.deliver_pending(sa2) == 0       # superseded: delivers none
    assert broker.deliver_pending(sb) == 2
    assert got_a == [] and got_b == [b"queued-1", b"queued-2"]
    # B is live now
    broker.publish("t", b"live", qos=1)
    assert got_b[-1] == b"live"


def test_shared_subscription_skips_offline_members():
    """HiveMQ routes a $share group's message to a CONNECTED member; an
    offline persistent member must not swallow its rotation share."""
    from iotml.mqtt.broker import MqttBroker, QueueClient

    broker = MqttBroker()
    live1 = QueueClient(broker, "live1", clean_start=False)
    live2 = QueueClient(broker, "live2", clean_start=False)
    gone = QueueClient(broker, "gone", clean_start=False)
    for c in (live1, live2, gone):
        c.subscribe("$share/g/t", qos=1)
    broker.disconnect("gone")

    for i in range(12):
        broker.publish("t", f"m{i}".encode(), qos=1)
    # every message went to a live member; nothing piled up for the corpse
    assert len(live1.messages) + len(live2.messages) == 12
    assert len(broker._offline["gone"][0]) == 0
    # ...but with NO live members, the group's traffic queues
    broker.disconnect("live1")
    broker.disconnect("live2")
    broker.publish("t", b"all-offline", qos=1)
    queued = sum(len(e[0]) for e in broker._offline.values())
    assert queued == 1


def test_connack_reports_session_present_on_resume():
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.wire import MqttServer
    import socket
    import struct

    from iotml.mqtt.wire import connect_packet

    broker = MqttBroker()

    def raw_connect(clean):
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.sendall(connect_packet("dev-1", 4, clean=clean))
        hdr = s.recv(2)
        assert hdr[0] >> 4 == 2  # CONNACK
        body = s.recv(hdr[1])
        return s, body[0] & 0x01  # session-present bit

    with MqttServer(broker) as srv:
        s1, present1 = raw_connect(clean=False)
        assert present1 == 0  # first connect: nothing to resume
        # subscribe so there is server-side state to resume
        from iotml.mqtt.wire import MqttClient
        s1.close()
        c = MqttClient("127.0.0.1", srv.port, "dev-1", clean=False)
        c.subscribe("t", qos=1)
        c.disconnect()
        import time as _t
        deadline = _t.time() + 5
        while broker.session_count() and _t.time() < deadline:
            _t.sleep(0.02)
        s2, present2 = raw_connect(clean=False)
        assert present2 == 1  # resumed persistent session
        s2.close()
        s3, present3 = raw_connect(clean=True)
        assert present3 == 0  # clean start wipes it
        s3.close()


def test_empty_client_id_with_persistent_session_rejected():
    """§3.1.3-8: zero-byte client id requires a clean session — otherwise
    CONNACK 0x02 (identifier rejected)."""
    import socket

    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.wire import MqttServer, connect_packet

    broker = MqttBroker()
    with MqttServer(broker) as srv:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.sendall(connect_packet("", 4, clean=False))
        hdr = s.recv(2)
        body = s.recv(hdr[1])
        assert hdr[0] >> 4 == 2 and body[1] == 0x02
        s.close()
        # clean+empty is fine (anon id synthesized)
        s2 = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s2.sendall(connect_packet("", 4, clean=True))
        hdr = s2.recv(2)
        body = s2.recv(hdr[1])
        assert hdr[0] >> 4 == 2 and body[1] == 0x00
        s2.close()
