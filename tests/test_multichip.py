"""Multi-chip streaming training (ISSUE 15): partition-parallel feeds,
sharded step, device-side normalization, rebalance coverage, and the
atomic multi-device checkpoint manifest — on the suite's 8-virtual-
device CPU mesh (conftest)."""

import numpy as np
import pytest

import jax

from iotml.core.normalize import CAR_NORMALIZER, RAW_COLUMNS
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.parallel.distributed import assign_partitions
from iotml.parallel.mesh import make_mesh
from iotml.parallel.streaming import (MeshFeeds, ShardedStreamTrainer,
                                      bench_leg, data_axis_devices,
                                      leg_record, shard_mean_losses)
from iotml.stream.broker import Broker


def _fill(broker, topic="S", n_ticks=100, partitions=8, num_cars=50,
          failure_rate=0.01):
    gen = FleetGenerator(FleetScenario(num_cars=num_cars,
                                       failure_rate=failure_rate))
    return gen.publish(broker, topic, n_ticks=n_ticks,
                       partitions=partitions)


def _mesh(n):
    return make_mesh((n,), ("data",), devices=jax.devices()[:n])


# ------------------------------------------------- partition assignment
def test_assign_partitions_disjoint_exhaustive():
    """The static device→partition split is a partition of the
    partition set for every (P, D)."""
    for n_parts in (1, 3, 8, 10, 16):
        for n_dev in (1, 2, 4, 8):
            subsets = [assign_partitions(n_parts, n_dev, d)
                       for d in range(n_dev)]
            flat = [p for s in subsets for p in s]
            assert sorted(flat) == list(range(n_parts))  # exhaustive
            assert len(flat) == len(set(flat))           # disjoint


def test_mesh_feeds_static_ownership_and_coverage():
    """4 feeds over 8 partitions: disjoint+exhaustive ownership, and a
    full drain consumes every filtered record exactly once."""
    broker = Broker()
    n = _fill(broker, n_ticks=40)
    feeds = MeshFeeds(broker, "S", 4, group="own", only_normal=False,
                      batch_size=50)
    owned = [set(p) for p in feeds.partitions]
    assert set().union(*owned) == set(range(8))
    assert sum(len(p) for p in owned) == 8
    total = 0
    for row in feeds.rounds():
        total += sum(b.n_valid for b in row if b is not None)
    assert total == n


def test_feed_rebalance_member_death_stays_disjoint_exhaustive():
    """The consumer-group mode under a mid-epoch member death — the
    cluster fleet's kill(i) semantics (stop driving WITHOUT leaving the
    group): after the session timeout expires the member, survivors'
    partition subsets must still be disjoint AND exhaustive, and the
    dead feed's partitions must keep flowing."""
    from iotml.stream.group import GroupCoordinator

    clock = [0.0]
    broker = Broker()
    n = _fill(broker, n_ticks=40)
    coord = GroupCoordinator(broker, "mesh-elastic",
                             session_timeout_s=5.0,
                             clock=lambda: clock[0])
    feeds = MeshFeeds(broker, "S", 4, group="mesh-elastic",
                      coordinator=coord, only_normal=False,
                      batch_size=50)
    assigned = feeds.assignments()
    flat = [tp for a in assigned for tp in a]
    assert len(flat) == 8 and len(set(flat)) == 8
    # mid-epoch: every member consumes a little, then member 2 dies
    seen = set()
    for c in feeds.consumers:
        for m in c.poll(60):
            seen.add((m.topic, getattr(m, "partition", 0), m.offset))
    dead = 2
    dead_parts = set(tp for tp in feeds.consumers[dead].assignment)
    # kill(i): the member is never driven again, never leaves cleanly.
    # Survivors keep heartbeating while the wall clock passes the dead
    # member's session timeout (sub-timeout steps: only the corpse
    # expires), then converge on the post-expiry generation.
    survivors = [c for i, c in enumerate(feeds.consumers) if i != dead]
    for _ in range(14):
        clock[0] += 0.5
        for c in survivors:
            c.poll(1)
    for c in survivors:
        c.poll(1)  # adopt the converged post-expiry assignment
    live = [sorted(c.assignment) for c in survivors]
    flat = [tp for a in live for tp in a]
    assert sorted(flat) == sorted((("S", p)) for p in range(8)), live
    assert len(flat) == len(set(flat))  # disjoint across survivors
    # the dead member's partitions moved, not vanished
    inherited = set(flat) & dead_parts
    assert inherited == dead_parts
    # and records keep flowing from them
    drained = 0
    for _ in range(200):
        got = sum(len(c.poll(256)) for c in survivors)
        drained += got
        if not got:
            break
    assert drained > 0


# ---------------------------------------------- prefetcher placement
def test_prefetcher_whole_batch_follows_sharding():
    """The satellite fix pinned: x, y AND mask (the per-row weights)
    all land with the given sharding — none stays on the default
    device."""
    from iotml.data.dataset import Batch
    from iotml.data.prefetch import DevicePrefetcher

    mesh = _mesh(4)
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data"))
    bs = [Batch(x=np.zeros((8, 18), np.float32), n_valid=6,
                first_index=0, y=np.ones((8, 18), np.float32))
          for _ in range(2)]
    for (x, y, mask), b in DevicePrefetcher(iter(bs), sharding=sharding):
        for arr in (x, y, mask):
            assert arr.sharding.is_equivalent_to(sharding, arr.ndim), \
                arr.sharding
        assert float(np.asarray(mask).sum()) == b.n_valid
    # without a sharding everything lands on the default device,
    # mask included
    for (x, y, mask), _b in DevicePrefetcher(iter([bs[0]])):
        assert x.devices() == mask.devices() == y.devices()


def test_global_put_lands_shards_on_their_devices():
    """Feed d's rows must live ONLY on data-axis device d."""
    from iotml.models.autoencoder import CAR_AUTOENCODER

    broker = Broker()
    _fill(broker, n_ticks=20)
    mesh = _mesh(4)
    feeds = MeshFeeds(broker, "S", 4, group="placement",
                      batch_size=10, only_normal=False)
    tr = ShardedStreamTrainer(CAR_AUTOENCODER, mesh, feeds)
    shards = [np.full((10, 18), float(d), np.float32) for d in range(4)]
    arr = tr._global_put(shards)
    assert arr.shape == (40, 18)
    devs = data_axis_devices(mesh)
    by_dev = {s.device: s for s in arr.addressable_shards}
    for d, dev in enumerate(devs):
        piece = np.asarray(by_dev[dev].data)
        assert np.all(piece == float(d))
        assert by_dev[dev].index[0] == slice(d * 10, (d + 1) * 10)


# ------------------------------------------- device-side normalization
def test_device_normalize_bit_comparable_losses():
    """The acceptance pin: device-side normalization (raw columns +
    affine fold in the jitted step, float32) against the host-
    normalized baseline (float64 math rounded once to float32) — the
    normalized inputs agree to ~1 ulp and the training losses are
    bit-comparable at every step."""
    from iotml.models.autoencoder import CAR_AUTOENCODER

    broker = Broker()
    _fill(broker, n_ticks=60, failure_rate=0.0)
    mesh = _mesh(4)

    def run(device_normalize, group):
        feeds = MeshFeeds(broker, "S", 4, group=group, batch_size=50,
                          take_batches=3, only_normal=True,
                          device_normalize=device_normalize)
        tr = ShardedStreamTrainer(
            CAR_AUTOENCODER, mesh, feeds,
            normalizer=CAR_NORMALIZER if device_normalize else None)
        losses = []
        for _ in range(4):  # 4 rounds x 3 batches/feed
            h = tr.fit_round()
            losses.extend(h["step_loss"])
        return losses

    host = run(False, "norm-host")
    dev = run(True, "norm-dev")
    assert len(host) == len(dev) and len(host) >= 8
    diffs = np.abs(np.asarray(host) - np.asarray(dev))
    # first step: pure normalization rounding (params identical)
    assert diffs[0] <= 5e-6, (host[0], dev[0])
    # whole run: divergence stays at float32-rounding scale
    assert diffs.max() <= 5e-4, diffs
    # and the map itself agrees to ~1 ulp on raw decoded columns
    raw = np.random.default_rng(0).uniform(-40, 260,
                                           (64, 18)).astype(np.float32)
    host_norm = CAR_NORMALIZER.np(raw)
    dev_norm = np.asarray(
        (raw * CAR_NORMALIZER.scale + CAR_NORMALIZER.shift)
        * CAR_NORMALIZER.mask, np.float32)
    assert np.abs(host_norm - dev_norm).max() <= 4e-5


def test_raw_columns_normalizer_is_passthrough():
    x = np.random.default_rng(1).normal(size=(5, 18)).astype(np.float32)
    out = RAW_COLUMNS.np(x)
    assert out is x  # cast-only view: zero host work
    assert np.array_equal(np.asarray(RAW_COLUMNS(x)), x)


# ----------------------------------------------------- sharded training
def test_sharded_stream_trainer_trains_and_tracks():
    from iotml.models.autoencoder import CAR_AUTOENCODER

    broker = Broker()
    n = _fill(broker, n_ticks=120, failure_rate=0.0)
    mesh = _mesh(4)
    feeds = MeshFeeds(broker, "S", 4, group="train", batch_size=50,
                      only_normal=True, device_normalize=True)
    tr = ShardedStreamTrainer(CAR_AUTOENCODER, mesh, feeds,
                              normalizer=CAR_NORMALIZER)
    h = tr.fit_round()
    assert h["records"][0] == n
    assert h["step_loss"][-1] < h["step_loss"][0]
    # positions advanced over every partition, per-chip losses published
    assert feeds.positions() and all(off > 0
                                     for _t, _p, off in feeds.positions())
    assert tr.last_shard_losses is not None
    assert len(tr.last_shard_losses) == 4
    assert np.all(np.isfinite(tr.last_shard_losses))


def test_feeds_device_normalize_requires_step_normalizer():
    from iotml.models.autoencoder import CAR_AUTOENCODER

    broker = Broker()
    _fill(broker, n_ticks=5)
    feeds = MeshFeeds(broker, "S", 2, group="guard",
                      device_normalize=True)
    with pytest.raises(ValueError, match="raw columns"):
        ShardedStreamTrainer(CAR_AUTOENCODER, _mesh(2), feeds)


def test_streaming_mesh_refuses_model_axis():
    mesh = make_mesh((4, 2), ("data", "model"),
                     devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="data-parallel"):
        data_axis_devices(mesh)


# -------------------------------------- continuous trainer integration
def test_continuous_trainer_mesh_manifest_is_atomic(tmp_path):
    """One checkpoint manifest stamps EVERY device's partition cursors
    (the PR 7 checkpointer gathering the sharded state host-side), and
    a second incarnation resumes from it."""
    from iotml.mlops import ModelRegistry
    from iotml.train.live import ContinuousTrainer

    broker = Broker()
    _fill(broker, n_ticks=200)
    mesh = _mesh(4)
    reg = ModelRegistry(str(tmp_path / "reg"))
    tr = ContinuousTrainer(broker, "S", None, registry=reg, mesh=mesh,
                           device_normalize=True, take_batches=4,
                           batch_size=50)
    assert tr.train_round()["records"] > 0
    v = tr.checkpointer.write_once()
    m = reg.manifest(v)
    stamped = {(t, p) for t, p, _ in m.offsets}
    assert stamped == {("S", p) for p in range(8)}  # ALL devices' parts
    # committed trails (never leads) the manifest
    for t, p, off in m.offsets:
        assert (broker.committed(tr.group, t, p) or 0) <= off
    tr.close()

    tr2 = ContinuousTrainer(broker, "S", None, registry=reg, mesh=mesh,
                            device_normalize=True, take_batches=4,
                            batch_size=50)
    assert tr2.restored_version == v
    pos = dict(((t, p), o) for t, p, o in tr2.consumer.positions())
    for t, p, off in m.offsets:
        assert pos[(t, p)] >= off  # forward-only resume
    tr2.close()


def test_continuous_trainer_mesh_rejects_multi_epoch_rounds():
    from iotml.train.live import ContinuousTrainer

    broker = Broker()
    _fill(broker, n_ticks=5)
    with pytest.raises(ValueError, match="single-epoch"):
        ContinuousTrainer(broker, "S", None, registry=object(),
                          mesh=_mesh(2), epochs_per_round=2)
    # same contract as OnlineLearner: no silent host-normalize fallback
    with pytest.raises(ValueError, match="needs a mesh"):
        ContinuousTrainer(broker, "S", None, registry=object(),
                          device_normalize=True)


# ------------------------------------------------ online per-chip drift
def test_online_mesh_per_chip_drift_coordinates_one_episode():
    """A chip-LOCAL drift (one shard's rows off-distribution) trips
    that chip's detector while the dulled global monitor stays quiet;
    the learner opens exactly ONE coordinated episode (tagged with the
    chip), boosts, and stages a forced registry publish."""
    from iotml.data.dataset import Batch
    from iotml.online.detectors import ADAPTING, DriftMonitor
    from iotml.online.learner import OnlineLearner

    broker = Broker()
    _fill(broker, n_ticks=10)
    mesh = _mesh(4)
    # global monitor deliberately blind (huge threshold, level rule off)
    blind = DriftMonitor(detector="ph", ph_threshold=1e9, level_ratio=0)
    lr = OnlineLearner(broker, "S", mesh=mesh, device_normalize=True,
                       window=100, monitor=blind,
                       chip_monitors=[DriftMonitor(burn_in=4)
                                      for _ in range(4)])
    rng = np.random.default_rng(0)

    def window(chip_spike=None):
        x = rng.normal(0, 0.1, (100, 18)).astype(np.float32)
        if chip_spike is not None:
            x[chip_spike * 25:(chip_spike + 1) * 25] += 60.0
        return Batch(x=x, n_valid=100, first_index=0)

    for _ in range(16):  # establish per-chip baselines
        loss = lr._update(window())
        lr._after_update(loss)
    assert lr.adaptations == []
    for _ in range(8):  # chip-3-local drift
        loss = lr._update(window(chip_spike=3))
        lr._after_update(loss)
    assert len(lr.adaptations) == 1, lr.adaptations
    _idx, signal, _action = lr.adaptations[0]
    assert signal.startswith("chip3-"), signal
    assert lr.monitor.state == ADAPTING  # ONE coordinated episode
    assert lr.current_lr > lr.base_lr   # boost applied
    assert lr._publish_pending and lr._publish_force  # registry push


def test_online_mesh_trains_from_stream():
    from iotml.online.learner import OnlineLearner

    broker = Broker()
    n = _fill(broker, n_ticks=60, failure_rate=0.0)
    lr = OnlineLearner(broker, "S", mesh=_mesh(4),
                       device_normalize=True, window=100)
    got = lr.process_available()
    assert got > 0 and lr.records_trained == n
    assert lr.last_chip_losses is not None
    assert len(lr.last_chip_losses) == 4
    d = lr.describe()
    assert len(d["chips"]) == 4


def test_cardata_cli_honors_mesh_knob_env(tmp_path, monkeypatch, capsys):
    """The deploy manifests' contract (deploy/model-training*.yaml:
    env IOTML_MESH_DATA=N ⇒ the Job trains over an N-data-axis mesh)
    must survive the knob's move into non_config: cli/_app reads the
    process knob and still builds the mesh."""
    from iotml.cli import cardata

    monkeypatch.setenv("IOTML_MESH_DATA", "2")
    rc = cardata.main(["--train.epochs=1", "--train.take_batches=2",
                       "--train.batch_size=50", "emulator:500",
                       "SENSOR_DATA_S_AVRO", "0", "model-predictions",
                       "train", "mesh-knob-model",
                       str(tmp_path / "arts")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "mesh: {'data': 2, 'model': 1}" in out, out


# --------------------------------------------------------------- knobs
def test_mesh_knobs_never_leak_into_config_tree():
    """IOTML_MESH_DATA / IOTML_DEVICE_NORMALIZE are process toggles in
    config's non_config set: neither rejected nor applied."""
    from iotml.config import load_config

    cfg, _ = load_config(argv=[], env={"IOTML_MESH_DATA": "4",
                                       "IOTML_DEVICE_NORMALIZE": "1"})
    clean, _ = load_config(argv=[], env={})
    assert cfg.as_dict() == clean.as_dict()
    assert cfg.applied == set()


def test_mesh_knob_validation(monkeypatch):
    from iotml.data import pipeline as pl

    monkeypatch.setenv("IOTML_MESH_DATA", "4")
    monkeypatch.setenv("IOTML_DEVICE_NORMALIZE", "1")
    assert pl.mesh_data() == 4
    assert pl.device_normalize() is True
    monkeypatch.setenv("IOTML_MESH_DATA", "-1")
    with pytest.raises(ValueError):
        pl.mesh_data()
    monkeypatch.setenv("IOTML_DEVICE_NORMALIZE", "maybe")
    with pytest.raises(ValueError):
        pl.device_normalize()
    monkeypatch.delenv("IOTML_MESH_DATA")
    monkeypatch.delenv("IOTML_DEVICE_NORMALIZE")
    assert pl.mesh_data() == 0
    assert pl.device_normalize() is False
    # the CLI bridge validates BEFORE publishing
    with pytest.raises(ValueError):
        pl.set_knobs(mesh_data=-2)
    assert "IOTML_MESH_DATA" not in __import__("os").environ
    pl.set_knobs(mesh_data=2, device_normalize=True)
    try:
        assert pl.mesh_data() == 2 and pl.device_normalize() is True
    finally:
        __import__("os").environ.pop("IOTML_MESH_DATA", None)
        __import__("os").environ.pop("IOTML_DEVICE_NORMALIZE", None)


# --------------------------------------------------------- bench schema
def test_bench_leg_matches_shared_schema():
    """bench_multichip legs and the MULTICHIP_r* harness legs must stay
    comparable: both come from leg_record, and bench_leg's output
    carries the shared keys."""
    leg = bench_leg(2, records=2000, warmup_records=1000, batch_size=50)
    shared = {"leg", "devices", "records", "seconds", "records_per_sec",
              "loss_first", "loss_last"}
    assert shared <= set(leg)
    assert leg["devices"] == 2 and leg["records"] > 0
    assert leg["records_per_sec"] > 0
    assert leg["loss_last"] < leg["loss_first"]
    ref = leg_record("x", 1, 10, 1.0, None, None)
    assert shared <= set(ref)


def test_bench_tables_consistent():
    """run_named derives from the same tables main() prints from —
    every directly-runnable bench must resolve to a known metric and a
    real function (the anti-drift pin)."""
    import bench

    units = {m for m, _u, _b in bench.METRIC_ORDER}
    for fn_name, metric in bench.SINGLE_BENCH.items():
        assert metric in units, (fn_name, metric)
        assert callable(getattr(bench, fn_name, None)), fn_name


def test_shard_mean_losses_maps_chips_in_feed_order():
    mesh = _mesh(4)
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data"))
    row = np.repeat(np.asarray([1.0, 2.0, 3.0, 4.0], np.float32), 8)
    arr = jax.device_put(row, sharding)
    out = shard_mean_losses(arr, [8, 8, 8, 8])
    assert np.allclose(out, [1.0, 2.0, 3.0, 4.0])
    # padding-aware: valid counts divide the masked sums
    out2 = shard_mean_losses(arr, [4, 8, 8, 8])
    assert np.isclose(out2[0], 2.0)
