"""Multi-host execution — 2 OS processes, jax.distributed over a local
coordinator, per-host partition consumers, cross-process gradient
all-reduce (VERDICT r1 item 4: the multi-host path must EXECUTE, not just
exist).

Topology: 2 processes × 2 virtual CPU devices = a 4-device ('data',) mesh
spanning both processes.  Each process consumes only its
`assign_partitions` share of a 4-partition topic from a real
KafkaWireServer over TCP, and drives `ShardedTrainer` steps whose
compiled all-reduce crosses the process boundary.  Both processes must
agree on the (replicated) loss and both must see it decrease.

The spawn/collect harness lives in
`iotml.parallel.multihost_worker.spawn_rehearsal`, shared with
`__graft_entry__`'s IOTML_DRYRUN_MULTIHOST leg.
"""

import re

import pytest

from iotml.parallel.multihost_worker import spawn_rehearsal


@pytest.mark.slow
def test_two_process_multihost_training():
    procs, outs = spawn_rehearsal()

    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} exited {p.returncode}:\n{out}"
        assert f"MULTIHOST pid={pid}/2 devices=4" in out, out

    # SPMD agreement: the replicated loss trajectory is identical on both
    # hosts (same global batches, same all-reduced gradients)
    losses = [re.search(r"loss ([\d.]+)->([\d.]+)", out).groups()
              for out in outs]
    assert losses[0] == losses[1], f"hosts disagree on loss: {losses}"


@pytest.mark.slow
def test_four_process_multihost_training():
    """The same rehearsal at 4 processes × 2 devices = an 8-device mesh:
    pins that nothing in the partition assignment, coordinator join, or
    global-batch assembly is hardwired to a 2-host world."""
    procs, outs = spawn_rehearsal(steps=4, n_procs=4, n_partitions=4)

    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} exited {p.returncode}:\n{out}"
        assert f"MULTIHOST pid={pid}/4 devices=8" in out, out

    losses = {re.search(r"loss ([\d.]+)->([\d.]+)", out).groups()
              for out in outs}
    assert len(losses) == 1, f"hosts disagree on loss: {losses}"
