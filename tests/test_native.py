"""C++ stream engine vs the pure-Python codec (the oracle): byte parity,
malformed-input handling, and the end-to-end fast path."""

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.ops.avro import AvroCodec
from iotml.ops.framing import frame
from iotml.stream import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native engine not built (no toolchain)")


def _records(n=32, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        r = {}
        for j, f in enumerate(KSQL_CAR_SCHEMA.fields):
            if f.name == "FAILURE_OCCURRED":
                r[f.name] = ["false", "true", ""][i % 3]
            elif f.avro_type in ("int", "long"):
                r[f.name] = int(rng.integers(-50, 3000))
            else:
                r[f.name] = float(rng.uniform(-100, 8000))
        recs.append(r)
    return recs


def test_decode_matches_python_oracle():
    py = AvroCodec(KSQL_CAR_SCHEMA)
    nat = native.NativeCodec(KSQL_CAR_SCHEMA)
    recs = _records()
    framed = [frame(py.encode(r)) for r in recs]
    num, lab = nat.decode_batch(framed, strip=5)
    cols = py.decode_batch([m[5:] for m in framed])
    np.testing.assert_allclose(num, py.sensor_matrix(cols), rtol=0, atol=0)
    assert [l.decode() for l in lab[:, 0]] == \
        [r["FAILURE_OCCURRED"] for r in recs]


def test_encode_matches_python_bytes():
    py = AvroCodec(KSQL_CAR_SCHEMA)
    nat = native.NativeCodec(KSQL_CAR_SCHEMA)
    recs = _records(8, seed=3)
    ref = [frame(py.encode(r)) for r in recs]
    num, lab = nat.decode_batch(ref, strip=5)
    out = nat.encode_batch(num, lab, schema_id=1)
    assert out == ref  # byte-for-byte wire parity


def test_nulls_decode_as_zero_and_empty():
    py = AvroCodec(KSQL_CAR_SCHEMA)
    nat = native.NativeCodec(KSQL_CAR_SCHEMA)
    msg = py.encode({f.name: None for f in KSQL_CAR_SCHEMA.fields})
    num, lab = nat.decode_batch([msg], strip=0)
    assert np.all(num == 0.0)
    assert lab[0, 0] == b""


def test_malformed_message_reports_row():
    nat = native.NativeCodec(KSQL_CAR_SCHEMA)
    py = AvroCodec(KSQL_CAR_SCHEMA)
    good = frame(py.encode(_records(1)[0]))
    with pytest.raises(ValueError, match="row 1"):
        nat.decode_batch([good, b"\x00\x00\x00\x00\x01\xff"], strip=5)


def test_overlong_varint_rejected():
    """A 10-byte varint whose final byte carries payload past bit 63 must be
    malformed, not silently truncated to a wrapped value: strict mode is the
    byte-parity gate for the rekey pass-through, and a varint the Python
    codec rejects must never validate natively."""
    nat = native.NativeCodec(KSQL_CAR_SCHEMA)
    # frame + 9 continuation bytes (payload 0) + final byte 0x7e: bits 1-6
    # land beyond bit 63.  Pre-fix this decoded as value 0 and "validated".
    hostile = b"\x00\x00\x00\x00\x01" + b"\x80" * 9 + b"\x7e"
    with pytest.raises(ValueError, match="row 0"):
        nat.decode_batch([hostile], strip=5)


def test_dataset_native_path_equals_python_path():
    """SensorBatches with and without the engine must emit identical batches."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=40, failure_rate=0.1))
    gen.publish(broker, "s", n_ticks=5)

    bs_nat = SensorBatches(StreamConsumer(broker, ["s:0:0"]), batch_size=64,
                           only_normal=True, keep_labels=True)
    assert bs_nat._native is not None
    bs_py = SensorBatches(StreamConsumer(broker, ["s:0:0"]), batch_size=64,
                          only_normal=True, keep_labels=True)
    bs_py._native = None  # force pure-Python fallback

    a, b = list(bs_nat), list(bs_py)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x.x, y.x)
        assert x.n_valid == y.n_valid and x.first_index == y.first_index
        assert list(x.labels) == list(y.labels)
