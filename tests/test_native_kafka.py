"""C++ Kafka wire client: cross-check against the Python client/server and
the pure-Python decode path (the correctness oracle), including the fused
fetch_decode hot path and the end-to-end SensorBatches pipeline."""

import numpy as np
import pytest

from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.kafka_wire import KafkaWireServer
from iotml.stream import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native stream engine not built")

from iotml.stream.native_kafka import (KafkaProtocolError,  # noqa: E402
                                       NativeKafkaBroker)


@pytest.fixture
def served():
    backing = Broker()
    with KafkaWireServer(backing) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        yield backing, client
        client.close()


def test_produce_fetch_offsets_roundtrip(served):
    backing, client = served
    client.create_topic("t", partitions=3)
    assert client.topic("t").partitions == 3
    assert client.produce("t", b"hello", key=b"car-1") == 0
    assert client.produce("t", b"world", key=b"car-1", timestamp_ms=7) == 1
    p = [p for p in range(3) if backing.end_offset("t", p) == 2][0]
    msgs = client.fetch("t", p, 0)
    assert [(m.value, m.key) for m in msgs] == \
        [(b"hello", b"car-1"), (b"world", b"car-1")]
    assert msgs[1].timestamp_ms == 7
    assert client.end_offset("t", p) == 2
    assert client.begin_offset("t", p) == 0
    assert [m.value for m in client.fetch("t", p, 1)] == [b"world"]
    # values containing NUL and empty values survive the wire
    client.create_topic("raw", partitions=1)
    payload = b"\x00\x01\xffdata\x00"
    client.produce("raw", payload, partition=0)
    client.produce("raw", b"", partition=0)
    vals = [m.value for m in client.fetch("raw", 0, 0)]
    assert vals == [payload, b""]
    # empty key and null key are distinct on the wire
    client.produce_many("raw", [(b"", b"ek", 0), (None, b"nk", 0)],
                        partition=0)
    keyed = {m.value: m.key for m in client.fetch("raw", 0, 2)}
    assert keyed == {b"ek": b"", b"nk": None}


def test_consumer_group_commit(served):
    _, client = served
    client.create_topic("t", partitions=1)
    assert client.committed("g", "t", 0) is None
    client.commit("g", "t", 0, 5)
    assert client.committed("g", "t", 0) == 5


def test_unknown_topic_and_idempotent_create(served):
    _, client = served
    with pytest.raises(KeyError):
        client.fetch("nope", 0, 0)
    client.create_topic("t", partitions=2)
    client.create_topic("t", partitions=2)  # TOPIC_EXISTS swallowed
    with pytest.raises(KeyError):
        client.topic("missing")


def test_sasl_plain():
    backing = Broker()
    backing.produce("t", b"secret")
    with KafkaWireServer(backing, credentials=("test", "test123")) as srv:
        ok = NativeKafkaBroker(f"127.0.0.1:{srv.port}",
                               sasl_username="test", sasl_password="test123")
        assert [m.value for m in ok.fetch("t", 0, 0)] == [b"secret"]
        ok.close()
        with pytest.raises(ConnectionError):
            NativeKafkaBroker(f"127.0.0.1:{srv.port}",
                              sasl_username="test", sasl_password="wrong")


def test_fetch_decode_matches_python_path(rng):
    """The fused C++ fetch+strip+decode equals poll() + NativeCodec +
    framing strip done separately."""
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    backing = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=7, failure_rate=0.3))
    gen.publish(backing, "sensors", n_ticks=30)
    codec = native.NativeCodec(KSQL_CAR_SCHEMA)
    with KafkaWireServer(backing) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        parts = client.topic("sensors").partitions
        for p in range(parts):
            msgs = client.fetch("sensors", p, 0, max_messages=4096)
            num, lab, next_off = client.fetch_decode(
                "sensors", p, 0, codec, strip=5, max_rows=4096)
            ref_num, ref_lab = codec.decode_batch(
                [m.value for m in msgs], strip=5)
            np.testing.assert_array_equal(num, ref_num)
            np.testing.assert_array_equal(lab, ref_lab)
            assert next_off == (msgs[-1].offset + 1 if msgs else 0)
        # EOF poll: zero rows, cursor unmoved
        end = client.end_offset("sensors", 0)
        num, lab, next_off = client.fetch_decode("sensors", 0, end, codec)
        assert len(num) == 0 and next_off == end
        client.close()


def test_sensor_batches_over_native_client():
    """Full pipeline over the native client: produce via generator,
    batches via the fused decode path, parity with the emulator run."""
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    backing = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=10, failure_rate=0.05))
    gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=40)

    def batches_from(broker):
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="g")
        return list(SensorBatches(consumer, batch_size=32, only_normal=True))

    ref = batches_from(backing)
    with KafkaWireServer(backing) as srv:
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}")
        got = batches_from(client)
        client.close()
    assert len(got) == len(ref) and len(got) > 0
    for b_ref, b_got in zip(ref, got):
        np.testing.assert_allclose(b_got.x, b_ref.x, rtol=1e-6)
        assert b_got.n_valid == b_ref.n_valid


def test_produce_many_multi_partition(served):
    backing, client = served
    client.create_topic("mp", partitions=4)
    entries = [(f"car-{i % 4}".encode(), f"v{i}".encode(), i) for i in range(20)]
    client.produce_many("mp", entries)
    total = sum(backing.end_offset("mp", p) for p in range(4))
    assert total == 20
    # keyed messages keep per-key ordering within their partition
    by_part = {}
    for p in range(4):
        for m in client.fetch("mp", p, 0):
            by_part.setdefault(m.key, []).append(m.value)
    for key, vals in by_part.items():
        idx = [int(v[1:]) for v in vals]
        assert idx == sorted(idx)


def test_concurrent_producer_and_consumer_share_one_client(served):
    """One socket + one staged buffer per handle: the client must serialize
    concurrent produce/fetch from different threads (the scorer's
    write-back-while-polling pattern)."""
    import threading

    _, client = served
    client.create_topic("cc", partitions=1)
    n, errors = 200, []

    def producer():
        try:
            for i in range(n):
                client.produce("cc", f"m{i}".encode(), partition=0)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def consumer():
        try:
            seen, off = 0, 0
            while seen < n:
                msgs = client.fetch("cc", 0, off)
                for m in msgs:
                    assert m.value == f"m{m.offset}".encode()
                seen += len(msgs)
                off += len(msgs)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=producer),
               threading.Thread(target=consumer)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert client.end_offset("cc", 0) == n


def test_commit_many_single_request_roundtrip():
    """Multi-partition offsets commit in ONE wire request and read back
    identically via committed() — from the native client, the Python wire
    client, and the StreamConsumer.commit() fast path over each."""
    from iotml.stream.kafka_wire import KafkaWireBroker

    broker = Broker()
    broker.create_topic("T", partitions=4)
    for p in range(4):
        for i in range(5):
            broker.produce("T", f"v{p}{i}".encode(), partition=p)
    with KafkaWireServer(broker) as srv:
        clients = [NativeKafkaBroker(f"127.0.0.1:{srv.port}"),
                   KafkaWireBroker(f"127.0.0.1:{srv.port}")]
        try:
            for j, client in enumerate(clients):
                g = f"g{j}"
                client.commit_many(g, "T", [(p, p + 1) for p in range(4)])
                assert [client.committed(g, "T", p)
                        for p in range(4)] == [1, 2, 3, 4]
                # the consumer's commit() groups cursors into this path
                c = StreamConsumer(client, [f"T:{p}:0" for p in range(4)],
                                   group=f"gc{j}")
                while c.poll(100):
                    pass
                c.commit()
                assert [client.committed(f"gc{j}", "T", p)
                        for p in range(4)] == [5, 5, 5, 5]
        finally:
            for client in clients:
                client.close()
