"""Native MQTT ingest engine (cpp/mqtt_ingest.cc) — protocol behavior,
payload parity with the Python fronts, and fan-in at connection count.

The engine is ingest-only (SURVEY L2's HiveMQ role for this pipeline:
absorb fleet publishes, hand payloads to the Kafka extension); full
broker semantics stay on the Python fronts."""

import socket
import struct
import threading
import time

import pytest

from iotml.mqtt.wire import (CONNACK, PUBACK, SUBACK, MqttClient,
                             connect_packet, publish_packet,
                             subscribe_packet)
from iotml.stream.broker import Broker

pytest.importorskip("ctypes")
native_ingest = pytest.importorskip("iotml.mqtt.native_ingest")
try:
    _probe = native_ingest.NativeMqttIngest()
    _probe.close()
except Exception:  # no toolchain → the pure-Python fronts remain
    pytest.skip("native stream engine unavailable", allow_module_level=True)


class _Pump:
    """Background poller: the engine only processes events inside poll(),
    so anything that waits for a server response needs one running."""

    def __init__(self, ing):
        self.ing = ing
        self.got = []
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            self.got.extend(self.ing.poll(timeout_ms=20))

    def stop(self):
        self._stop.set()
        self._t.join(timeout=5)


def test_connect_publish_qos0_and_1():
    with native_ingest.NativeMqttIngest() as ing:
        pump = _Pump(ing)
        try:
            c = MqttClient("127.0.0.1", ing.port, "car-1")
            c.publish("vehicles/sensor/data/car-1", b"p0", qos=0)
            c.publish("vehicles/sensor/data/car-1", b"p1", qos=1)  # waits PUBACK
            deadline = time.time() + 5
            while len(pump.got) < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert [(t.decode(), p) for t, p in pump.got] == [
                ("vehicles/sensor/data/car-1", b"p0"),
                ("vehicles/sensor/data/car-1", b"p1")]
            c.disconnect()
        finally:
            pump.stop()


def test_mqtt5_publish_with_properties():
    with native_ingest.NativeMqttIngest() as ing:
        pump = _Pump(ing)
        try:
            c = MqttClient("127.0.0.1", ing.port, "v5car", protocol_level=5)
            c.publish("vehicles/sensor/data/v5car", b"v5payload", qos=1)
            deadline = time.time() + 5
            while not pump.got and time.time() < deadline:
                time.sleep(0.02)
            assert pump.got == [(b"vehicles/sensor/data/v5car", b"v5payload")]
            c.disconnect()
        finally:
            pump.stop()


def test_subscribe_refused_with_failure_code():
    with native_ingest.NativeMqttIngest() as ing:
        pump = _Pump(ing)
        try:
            c = MqttClient("127.0.0.1", ing.port, "nosub")
            with pytest.raises(ValueError, match="rejected"):
                c.subscribe("vehicles/#", qos=0)
            c.disconnect()
        finally:
            pump.stop()


def test_qos2_publish_drops_connection():
    with native_ingest.NativeMqttIngest() as ing:
        s = socket.create_connection(("127.0.0.1", ing.port), timeout=5)
        s.sendall(connect_packet("q2"))
        s.settimeout(5)
        buf = b""
        while len(buf) < 4:
            ing.poll(timeout_ms=20)
            try:
                buf += s.recv(4 - len(buf))
            except socket.timeout:
                pass
        assert buf[0] >> 4 == CONNACK
        s.sendall(publish_packet("t", b"x", qos=2, packet_id=1))
        for _ in range(20):
            ing.poll(timeout_ms=20)
        assert s.recv(16) == b""  # dropped
        s.close()


def test_malformed_frame_drops_only_that_connection():
    with native_ingest.NativeMqttIngest() as ing:
        pump = _Pump(ing)
        try:
            bad = socket.create_connection(("127.0.0.1", ing.port),
                                           timeout=5)
            bad.sendall(b"\x30\xff\xff\xff\xff\xff")  # malformed varint
            bad.settimeout(5)
            assert bad.recv(16) == b""
            bad.close()
            # engine still serves others
            c = MqttClient("127.0.0.1", ing.port, "fine")
            c.publish("t/a", b"ok", qos=1)
            c.disconnect()
        finally:
            pump.stop()


def test_bridge_parity_and_filtering():
    """NativeIngestBridge forwards the same record shape KafkaBridge does
    and drops non-matching topics."""
    stream = Broker()
    with native_ingest.NativeIngestBridge(stream, partitions=2) as bridge:
        c = MqttClient("127.0.0.1", bridge.port, "car-9")
        c.publish("vehicles/sensor/data/car-9", b'{"v":1}', qos=1)
        c.publish("other/topic", b"nope", qos=1)
        c.publish("vehicles/sensor/data/car-9", b'{"v":2}', qos=0)
        deadline = time.time() + 10
        while bridge.forwarded() < 2 and time.time() < deadline:
            time.sleep(0.02)
        c.disconnect()
    assert bridge.forwarded() == 2
    msgs = []
    for p in range(2):
        msgs.extend(stream.fetch("sensor-data", p, 0, 100))
    assert sorted(m.value for m in msgs) == [b'{"v":1}', b'{"v":2}']
    assert all(m.key == b"vehicles/sensor/data/car-9" for m in msgs)


def test_many_connections_fanin_native():
    n_conns, per_conn = 300, 30
    stream = Broker()
    with native_ingest.NativeIngestBridge(stream, partitions=4) as bridge:
        barrier = threading.Barrier(n_conns)
        errors = []

        def run(i):
            try:
                s = socket.create_connection(("127.0.0.1", bridge.port),
                                             timeout=10)
                s.sendall(connect_packet(f"car-{i:05d}"))
                buf = b""
                while len(buf) < 4:
                    chunk = s.recv(4 - len(buf))
                    if not chunk:
                        raise ConnectionError("EOF before CONNACK")
                    buf += chunk
                barrier.wait(timeout=60)
                pkt = publish_packet(f"vehicles/sensor/data/car-{i:05d}",
                                     b"{}", qos=0)
                for _ in range(per_conn):
                    s.sendall(pkt)
                s.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                try:
                    barrier.abort()
                except Exception:
                    pass

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_conns)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]
        want = n_conns * per_conn
        deadline = time.time() + 30
        while bridge.forwarded() < want and time.time() < deadline:
            time.sleep(0.05)
        assert bridge.forwarded() == want
