"""Normalizer parity with the reference normalize_fn (cardata-v3.py:105-148)."""

import numpy as np
import pytest

from iotml.core.normalize import Normalizer, CAR_NORMALIZER
from iotml.core.schema import CAR_SCHEMA


def reference_normalize(row):
    """Literal per-field transcription of the reference's math, as the oracle."""
    def scale(v, lo, hi):
        return (v - lo) / (hi - lo) * 2.0 - 1.0

    (coolant, intake_t, intake_f, batt_pct, batt_v, cur, speed, vib, thr,
     tp11, tp12, tp21, tp22, a11, a12, a21, a22, fw) = row
    return np.array([
        0.0,
        scale(intake_t, 15.0, 40.0),
        0.0,
        scale(batt_pct, 0.0, 100.0),
        0.0,
        0.0,
        scale(speed, 0.0, 50.0),
        scale(vib, 0.0, 7500.0),
        scale(thr, 0.0, 1.0),
        scale(tp11, 20.0, 35.0), scale(tp12, 20.0, 35.0),
        scale(tp21, 20.0, 35.0), scale(tp22, 20.0, 35.0),
        scale(a11, 0.0, 7.0), scale(a12, 0.0, 7.0),
        scale(a21, 0.0, 7.0), scale(a22, 0.0, 7.0),
        scale(fw, 1000.0, 2000.0),
    ])


def test_parity_with_reference_math(rng):
    rows = rng.uniform(0, 100, size=(64, 18))
    expected = np.stack([reference_normalize(r) for r in rows])
    got = np.asarray(CAR_NORMALIZER(rows))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    # host-side numpy twin agrees with the jax path
    np.testing.assert_allclose(CAR_NORMALIZER.np(rows), got, rtol=1e-6, atol=1e-6)


def test_zeroed_fields_are_zero(rng):
    x = rng.uniform(-1e3, 1e3, size=(8, 18))
    out = np.asarray(CAR_NORMALIZER(x))
    for idx in (0, 2, 4, 5):  # coolant, air_flow, voltage, current
        assert np.all(out[:, idx] == 0.0)


def test_range_endpoints_map_to_unit_interval():
    x = np.zeros((1, 18))
    x[0, 1] = 15.0  # intake_air_temp lo
    out = np.asarray(CAR_NORMALIZER(x))
    assert out[0, 1] == pytest.approx(-1.0)
    x[0, 1] = 40.0
    assert np.asarray(CAR_NORMALIZER(x))[0, 1] == pytest.approx(1.0)


def test_non_parity_mode_calibrates_todo_fields(rng):
    n = Normalizer(CAR_SCHEMA, parity=False)
    x = rng.uniform(0, 100, size=(8, 18))
    out = np.asarray(n(x))
    assert not np.all(out[:, 0] == 0.0)  # coolant_temp now normalized
