"""Fleet-scope observability v2 (ISSUE 13): event-time watermarks on
the columnar plane, wire-carried batch traces, metrics federation, the
consumer-lag gauge, the columnar liveness fix, hot-loop profiling
phases, and the label-cardinality bound."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.obs import federate, metrics as obs_metrics, tracing, watermark
from iotml.ops import framing
from iotml.ops.avro import AvroCodec
from iotml.store import segment as seg
from iotml.stream import native as native_mod
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer
from iotml.stream.producer import RawBatchProducer

NATIVE = native_mod.available()
needs_native = pytest.mark.skipif(not NATIVE,
                                  reason="C++ engine not built")

CODEC = AvroCodec(KSQL_CAR_SCHEMA)
BASE_TS = 1_700_000_000_000  # a real wall-clock ms epoch


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset()
    yield
    tracing.configure(enabled=False, sample=1.0, path="")
    tracing.reset()


def _record(rng, label="false"):
    rec = {}
    for f in KSQL_CAR_SCHEMA.fields:
        if f.name == "FAILURE_OCCURRED":
            rec[f.name] = label
        elif f.avro_type in ("int", "long"):
            rec[f.name] = int(rng.integers(0, 40))
        else:
            rec[f.name] = float(rng.normal())
    return rec


def _frames(n=32, base_offset=0, ts0=BASE_TS, tombstone_at=()):
    rng = np.random.default_rng(5)
    out = []
    for i in range(n):
        key = f"car-{i % 5}".encode()
        if i in tombstone_at:
            out.append(seg.encode_record(base_offset + i, key, None,
                                         ts0 + i, None))
        else:
            payload = framing.frame(CODEC.encode(_record(rng)), 1)
            out.append(seg.encode_record(base_offset + i, key, payload,
                                         ts0 + i, None))
    return b"".join(out)


def _fill(broker, topic="T", n=64, partitions=1, ts0=BASE_TS):
    broker.create_topic(topic, partitions=partitions)
    rng = np.random.default_rng(3)
    for p in range(partitions):
        broker.produce_many(
            topic,
            [(f"car-{i % 5}".encode(),
              framing.frame(CODEC.encode(_record(rng)), 1), ts0 + i)
             for i in range(n)], partition=p)


# ------------------------------------------------- event-time watermarks
@needs_native
def test_frame_decoder_reports_event_time_bounds():
    """The native decoder's ts min/max out-params match the oracle,
    tombstones included (both advance the watermark)."""
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    dec = nc.frame_decoder()
    buf = _frames(n=24, tombstone_at=(3, 20))
    out_n = np.zeros((64, nc.n_numeric), np.float32)
    out_l = np.zeros((64, nc.n_strings), "S16")
    rows, next_off, flags, skipped = dec.decode_into(buf, 0, out_n, out_l)
    assert rows == 22 and skipped == 2 and next_off == 24
    assert (dec.last_ts_min, dec.last_ts_max) == (BASE_TS, BASE_TS + 23)
    # oracle parity (want_ts grows the tuple; the default stays 6-wide)
    *_, py_min, py_max = framing.decode_frames_columnar_py(
        buf, 0, KSQL_CAR_SCHEMA, want_ts=True)
    assert (py_min, py_max) == (BASE_TS, BASE_TS + 23)
    # a cursor past the head only counts consumed frames
    rows, *_ = dec.decode_into(buf, 10, out_n, out_l)
    assert dec.last_ts_min == BASE_TS + 10
    # nothing consumed → -1 sentinels
    rows, *_ = dec.decode_into(b"", 0, out_n, out_l)
    assert rows == 0 and dec.last_ts_min == -1 and dec.last_ts_max == -1


@needs_native
def test_poll_into_publishes_consume_watermark(tmp_path):
    """poll_into folds decoder event time into the consumer accumulation
    AND the consume-stage watermark metric, batch-granularly."""
    broker = Broker(store_dir=str(tmp_path))
    _fill(broker, n=48)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    cons = StreamConsumer(broker, ["T:0:0"], group="wm")
    out_n = np.zeros((4096, nc.n_numeric), np.float32)
    out_l = np.zeros((4096, nc.n_strings), "S16")
    key = ('iotml_watermark_lag_seconds_count'
           '{group="wm",partition="0",stage="consume",topic="T"}')
    before = obs_metrics.default_registry.collect().get(key, 0.0)
    rows, fb = cons.poll_into(nc.frame_decoder(), out_n, out_l)
    assert rows == 48
    taken = cons.take_event_time()
    assert taken == {("T", 0): (BASE_TS, BASE_TS + 47)}
    assert cons.take_event_time() == {}  # cleared on read
    after = obs_metrics.default_registry.collect().get(key, 0.0)
    assert after > before
    # the watermark gauge carries the newest processed event time,
    # group-labeled (two consumers of one partition are two frontiers)
    assert obs_metrics.watermark_event_ms.value(
        stage="consume", topic="T", partition=0,
        group="wm") == BASE_TS + 47
    broker.close()


def test_classic_poll_folds_event_time():
    """The classic message path folds batch-endpoint timestamps, so
    non-columnar consumers watermark too."""
    broker = Broker()
    _fill(broker, n=16)
    cons = StreamConsumer(broker, ["T:0:0"], group="wm2")
    msgs = cons.poll(1024)
    assert len(msgs) == 16
    assert cons.take_event_time() == {("T", 0): (BASE_TS, BASE_TS + 15)}
    assert obs_metrics.watermark_event_ms.value(
        stage="consume", topic="T", partition=0,
        group="wm2") == BASE_TS + 15


def test_observe_taken_rejects_open_vocabulary():
    with pytest.raises(ValueError):
        watermark.observe("car_17", "T", 0, BASE_TS, BASE_TS)


def test_scorer_drain_publishes_score_watermark(tmp_path):
    """A completed scorer drain takes the consumer's event-time ranges
    as the ingest→score watermark — e2e staleness with zero per-record
    cost."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    broker = Broker(store_dir=str(tmp_path))
    _fill(broker, n=120)
    broker.create_topic("OUT")
    cons = StreamConsumer(broker, ["T:0:0"], group="score-wm", eof=True)
    sb = SensorBatches(cons, batch_size=20, keep_labels=True)
    tr = Trainer(CAR_AUTOENCODER)
    tr._ensure_state(np.zeros((20, 18), np.float32))
    before = obs_metrics.watermark_event_ms.value(
        stage="score", topic="T", partition=0, group="score-wm")
    scorer = StreamScorer(CAR_AUTOENCODER, tr.state.params, sb,
                          OutputSequence(broker, "OUT"))
    n = scorer.score_available()
    assert n == 120
    assert obs_metrics.watermark_event_ms.value(
        stage="score", topic="T", partition=0,
        group="score-wm") == BASE_TS + 119 > before
    broker.close()


# -------------------------------------------------- columnar liveness fix
@needs_native
def test_columnar_consume_keeps_stage_liveness_fresh(tmp_path):
    """Regression (ISSUE 13 satellite): a traced session consuming over
    the COLUMNAR path materialises no records and forks no per-record
    spans — stage liveness must still see the consume stage beat, or
    /healthz reports a healthy pipeline as stalled."""
    tracing.configure(enabled=True, sample=1.0)
    broker = Broker(store_dir=str(tmp_path))
    _fill(broker, n=32)
    with KafkaWireServer(broker) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
        cons = StreamConsumer(wb, ["T:0:0"], group="live")
        out_n = np.zeros((4096, nc.n_numeric), np.float32)
        out_l = np.zeros((4096, nc.n_strings), "S16")
        rows, _fb = cons.poll_into(nc.frame_decoder(), out_n, out_l)
        assert rows == 32
        ages = tracing.liveness()
        assert "consume" in ages and ages["consume"] < 5.0
        wb.close()
    broker.close()


# ---------------------------------------------------- wire batch traces
def test_stamp_and_extract_first_frame_headers():
    buf = _frames(n=8)
    ctx = tracing.TraceContext()
    stamped = framing.stamp_first_frame(
        buf, (("iotml_trace", ctx.encode()),))
    hdrs = framing.first_frame_headers(stamped)
    assert hdrs and hdrs[0][0] == "iotml_trace"
    got = tracing.TraceContext.decode(hdrs[0][1])
    assert got is not None and got.trace_id == ctx.trace_id
    # the stamped batch still CRC-validates and restamps whole
    restamped, count, max_ts = framing.restamp_frame_batch(stamped, 100)
    assert count == 8 and max_ts == BASE_TS + 7
    # other frames untouched byte-for-byte
    entries = list(framing.iter_frame_entries(stamped))
    assert len(entries) == 8 and entries[1][4] is None


@needs_native
def test_wire_batch_trace_end_to_end(tmp_path):
    """RAW_PRODUCE → segment → RAW_FETCH → poll_into: one sampled batch
    trace survives the wire in frame headers, is marked at each hop,
    and closes with an e2e span at the pipeline closer."""
    spans = str(tmp_path / "spans.jsonl")
    tracing.configure(enabled=True, sample=1.0, path=spans)
    broker = Broker(store_dir=str(tmp_path / "store"))
    broker.create_topic("T", partitions=1)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    rng = np.random.default_rng(0)
    n = 40
    numeric = rng.normal(size=(n, nc.n_numeric))
    labels = np.full((n, nc.n_strings), b"false", "S16")
    ts = np.arange(BASE_TS, BASE_TS + n, dtype=np.int64)
    frames = nc.encode_frames(numeric, labels, timestamps=ts, schema_id=1)
    with KafkaWireServer(broker) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        prod = RawBatchProducer(wb, "T")
        base = prod.produce_frames(0, frames, n)
        assert base == 0 and prod.engaged is True
        cons = StreamConsumer(wb, ["T:0:0"], group="bt")
        out_n = np.zeros((4096, nc.n_numeric), np.float32)
        out_l = np.zeros((4096, nc.n_strings), "S16")
        # drain in SLICES smaller than the batch: later raw reads are
        # sparse-index aligned and re-serve the stamped batch head —
        # the cursor gate must extract the context exactly ONCE
        dec = nc.frame_decoder()
        total = 0
        while True:
            rows, _fb = cons.poll_into(dec, out_n, out_l, max_rows=16)
            if rows == 0:
                break
            total += rows
        assert total == n
        traces = cons.take_batch_traces()
        assert len(traces) == 1
        for ctx in traces:
            ctx.close("score")
        wb.close()
    tracing.flush()
    stages = set()
    kinds = set()
    for line in open(spans):
        doc = json.loads(line)
        kinds.add(doc["kind"])
        if doc["kind"] == "span":
            stages.add(doc["stage"])
        assert "proc" in doc or doc["kind"] not in ("span", "e2e")
    assert {"raw_produce", "raw_produce_append", "wire_raw_produce",
            "wire_raw_fetch", "consume", "score"} <= stages
    assert "batch" in kinds and "e2e" in kinds
    broker.close()


def test_trace_cli_cross_process_reconstruction(tmp_path, capsys):
    """`iotml.obs trace --require-cross-process N` passes on a log whose
    closed trace spans N procs and fails otherwise."""
    from iotml.obs.__main__ import main as obs_main

    path = str(tmp_path / "fleet.jsonl")
    tid = "00000000deadbeef"
    lines = [
        {"kind": "span", "trace": tid, "stage": "raw_produce",
         "start_us": 0, "dur_us": 80, "wall0_ns": 1, "proc": "bridge"},
        {"kind": "span", "trace": tid, "stage": "wire_raw_fetch",
         "start_us": 120, "dur_us": 10, "wall0_ns": 1, "proc": "shard-0"},
        {"kind": "span", "trace": tid, "stage": "consume",
         "start_us": 200, "dur_us": 40, "wall0_ns": 1, "proc": "scorer"},
        {"kind": "batch", "trace": tid, "stage": "consume", "topic": "T",
         "partition": 0, "first_offset": 0, "last_offset": 39, "n": 40,
         "wall0_ns": 1, "proc": "scorer"},
        {"kind": "span", "trace": tid, "stage": "score",
         "start_us": 260, "dur_us": 500, "wall0_ns": 1, "proc": "scorer"},
        {"kind": "e2e", "trace": tid, "closer": "score", "dur_us": 760,
         "wall0_ns": 1, "proc": "scorer"},
    ]
    with open(path, "w") as fh:
        fh.write("\n".join(json.dumps(d) for d in lines) + "\n")
    assert obs_main(["trace", path, "--require-cross-process", "3",
                     "--show-trace"]) == 0
    out = capsys.readouterr().out
    assert "3 process(es)" in out and "shard-0" in out
    assert "offsets 0-39" in out
    assert obs_main(["trace", path, "--require-cross-process", "4"]) == 1


# ---------------------------------------------------------- consumer lag
@needs_native
def test_raw_fetch_carries_hwm(tmp_path):
    """The columnar path feeds consumer lag with ZERO extra round
    trips: RAW_FETCH responses carry the hwm as a trailing-optional
    field, so a pure-poll_into consumer never needs end_offset."""
    broker = Broker(store_dir=str(tmp_path))
    _fill(broker, n=40)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    with KafkaWireServer(broker) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        cons = StreamConsumer(wb, ["T:0:0"], group="rawlag")
        out_n = np.zeros((16, nc.n_numeric), np.float32)
        out_l = np.zeros((16, nc.n_strings), "S16")
        rows, _fb = cons.poll_into(nc.frame_decoder(), out_n, out_l,
                                   max_rows=16)
        assert rows == 16
        assert wb.last_hwm("T", 0) == 40  # from the RAW_FETCH response
        assert cons.record_lag() == 24
        wb.close()
    broker.close()


def test_consumer_lag_gauge_wire_and_local(tmp_path):
    broker = Broker(store_dir=str(tmp_path))
    _fill(broker, n=50)
    with KafkaWireServer(broker) as srv:
        wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        cons = StreamConsumer(wb, ["T:0:0"], group="lagg")
        cons.poll(20)
        # classic fetch cached the hwm: record_lag needs no round trip
        assert wb.last_hwm("T", 0) == 50
        total = cons.record_lag()
        assert total == 30
        assert obs_metrics.consumer_lag_records.value(
            group="lagg", topic="T", partition=0) == 30
        cons.commit()  # commit refreshes too
        wb.close()
    # in-process broker: end_offset fallback
    cons2 = StreamConsumer(broker, ["T:0:10"], group="lagh")
    assert cons2.record_lag() == 40
    broker.close()


def test_healthz_carries_watermarks_and_lag(tmp_path):
    obs_metrics.watermark_event_ms.set(BASE_TS, stage="twin", topic="T",
                                       partition=2)
    obs_metrics.consumer_lag_records.set(11, group="g2", topic="T",
                                         partition=2)
    srv = obs_metrics.start_http_server(0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.server_address[1]}/healthz").read()
        doc = json.loads(body)
        assert doc["watermarks"]["twin:T:2"]["event_time_ms"] == BASE_TS
        assert doc["watermarks"]["twin:T:2"]["lag_s"] > 0
        assert doc["consumer_lag_records"]["g2:T:2"] == 11
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------------ federation
def test_prom_text_parser_roundtrip():
    text = ('# HELP x h\n# TYPE iotml_x_total counter\n'
            'iotml_x_total{topic="a\\"b",stage="s"} 3.5\n'
            'iotml_plain 1\n'
            'garbage line without value\n')
    types, samples = federate.parse_prom_text(text)
    assert types == {"iotml_x_total": "counter"}
    assert ("iotml_x_total", {"topic": 'a"b', "stage": "s"}, 3.5) in samples
    assert ("iotml_plain", {}, 1.0) in samples


def test_federation_merges_and_rolls_up(tmp_path):
    srv = obs_metrics.start_http_server(0)
    obs_metrics.records_scored.inc(25)
    obs_metrics.consumer_lag_records.set(4, group="fg", topic="FT",
                                         partition=1)
    addr = f"127.0.0.1:{srv.server_address[1]}"
    try:
        col = federate.FleetCollector(
            endpoints=[{"name": "a", "address": addr},
                       {"name": "b", "address": addr},
                       {"name": "dead", "address": "127.0.0.1:1"}])
        snaps = col.collect()
        text = col.render(snaps)
        assert 'iotml_cluster_up{process="dead"} 0' in text
        assert "iotml_cluster_processes 2" in text
        assert 'iotml_records_scored_total{process="a"}' in text
        assert "iotml_cluster_records_scored_total" in text
        lag_line = [l for l in text.splitlines()
                    if l.startswith("iotml_cluster_consumer_lag_records")
                    and 'group="fg"' in l]
        assert lag_line and lag_line[0].endswith(" 8.0")  # 4 × 2 procs
        hz = col.healthz(snaps)
        assert hz["up_count"] == 2 and "dead" in hz["degraded"]
        # compacted changelog: snapshot + replay
        broker = Broker()
        col.snapshot_changelog(broker, snaps)
        assert broker.topic(federate.METRICS_TOPIC).cleanup_policy == \
            "compact"
        state = federate.read_fleet_state(broker)
        assert state["a"]["up"] is True and "dead" in state
    finally:
        srv.shutdown()
        srv.server_close()


def test_fleet_cli_once_and_manifest(tmp_path, capsys):
    from iotml.obs.__main__ import main as obs_main

    srv = obs_metrics.start_http_server(0)
    man = str(tmp_path / "endpoints.json")
    addr = f"127.0.0.1:{srv.server_address[1]}"
    federate.publish_endpoint(man, "p1", addr)
    federate.publish_endpoint(man, "p2", addr)
    federate.publish_endpoint(man, "p1", addr)  # replace, not duplicate
    assert [e["name"] for e in federate.load_manifest(man)] == ["p1", "p2"]
    try:
        assert obs_main(["fleet", "--endpoints", man, "--once",
                         "--min-processes", "2"]) == 0
        capsys.readouterr()
        assert obs_main(["fleet", "--endpoints", man, "--once",
                         "--min-processes", "3"]) == 1
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------- metrics server under load
def test_metrics_server_concurrent_scrape():
    """N scraper threads hammer /metrics + /healthz while workers mutate
    every metric type: every response parses, no 5xx, no exception."""
    srv = obs_metrics.start_http_server(0)
    port = srv.server_address[1]
    stop = threading.Event()
    errors = []

    def work():
        i = 0
        while not stop.is_set():
            obs_metrics.records_consumed.inc()
            obs_metrics.watermark_event_ms.set(BASE_TS + i, stage="consume",
                                               topic="CT", partition=0)
            obs_metrics.step_seconds.observe(0.001, loop="score",
                                             phase="device_compute")
            i += 1

    def scrape(path):
        try:
            for _ in range(20):
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5).read()
                if path == "/healthz":
                    json.loads(body)
                else:
                    federate.parse_prom_text(body.decode())
        except Exception as e:  # noqa: BLE001 - collected for assert
            errors.append(e)

    workers = [threading.Thread(target=work, daemon=True)
               for _ in range(2)]
    scrapers = [threading.Thread(target=scrape, args=(p,), daemon=True)
                for p in ("/metrics", "/healthz", "/metrics")]
    try:
        for t in workers + scrapers:
            t.start()
        for t in scrapers:
            t.join(timeout=30)
    finally:
        stop.set()
        for t in workers:
            t.join(timeout=5)
        srv.shutdown()
        srv.server_close()
    assert not errors


# -------------------------------------------------- cardinality bound
def test_label_cardinality_bound():
    """Labels come from closed sets: the default registry is clean, and
    a runaway car_id-style label fails the check before it fails
    production."""
    assert obs_metrics.cardinality_violations(
        obs_metrics.default_registry) == []
    reg = obs_metrics.Registry()
    c = reg.counter("iotml_bad_total")
    c.inc(**{"car_id": "car-1"})
    v = obs_metrics.cardinality_violations(reg)
    assert v and "car_id" in v[0][1]
    # series-count bound: one value per "entity" explodes
    reg2 = obs_metrics.Registry()
    g = reg2.gauge("iotml_worse")
    for i in range(obs_metrics.MAX_LABEL_SERIES + 1):
        g.set(1.0, **{"topic": f"t{i}"})
    v2 = obs_metrics.cardinality_violations(reg2)
    assert v2 and "cardinality bound" in v2[0][1]


# ------------------------------------------------- profiling hot loops
def test_step_seconds_phases_recorded(tmp_path):
    """A train round and a prefetcher pass populate the
    loop×phase step histogram and the occupancy gauge."""
    from iotml.data.dataset import Batch
    from iotml.data.prefetch import DevicePrefetcher

    before = obs_metrics.default_registry.collect()
    batches = [Batch(np.zeros((4, 18), np.float32), 4, i * 4)
               for i in range(3)]
    with DevicePrefetcher(iter(batches), depth=2, loop="score") as pf:
        assert len(list(pf)) == 3
    after = obs_metrics.default_registry.collect()
    key = 'iotml_step_seconds_count{loop="score",phase="host_wait"}'
    # one observation per dequeue (3 batches + the end sentinel)
    assert after.get(key, 0.0) - before.get(key, 0.0) == 4.0
    assert "iotml_prefetch_occupancy" in \
        obs_metrics.default_registry.render()


def test_fit_compiled_records_device_and_host_phases(tmp_path):
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.train.loop import Trainer
    from iotml.data.dataset import Batch

    before = obs_metrics.default_registry.collect()
    batches = [Batch(np.random.default_rng(1).normal(
        size=(8, 18)).astype(np.float32), 8, i * 8) for i in range(2)]
    Trainer(CAR_AUTOENCODER).fit_compiled(batches, epochs=1)
    after = obs_metrics.default_registry.collect()
    for phase in ("host_pipeline", "device_compute"):
        key = f'iotml_step_seconds_count{{loop="train",phase="{phase}"}}'
        assert after.get(key, 0.0) > before.get(key, 0.0), phase
