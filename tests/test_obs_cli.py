"""Observability (Prometheus text format, scalar logs) and the
reference-compatible CLI."""

import json
import urllib.request

from iotml.obs.metrics import Registry, start_http_server
from iotml.obs.tb import ScalarLogger
from iotml.cli.cardata import main as cardata_main


def test_registry_render_prometheus_text():
    reg = Registry()
    c = reg.counter("iotml_records_consumed_total", "records")
    c.inc(5, topic="sensor-data")
    c.inc(2, topic="sensor-data")
    g = reg.gauge("iotml_reconstruction_mse", "mse")
    g.set(0.25)
    h = reg.histogram("iotml_train_step_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert 'iotml_records_consumed_total{topic="sensor-data"} 7.0' in text
    assert "# TYPE iotml_reconstruction_mse gauge" in text
    assert 'iotml_train_step_seconds_bucket{le="0.1"} 1' in text
    assert 'iotml_train_step_seconds_bucket{le="+Inf"} 3' in text
    assert "iotml_train_step_seconds_count 3" in text


def test_label_value_escaping_per_exposition_spec():
    """Regression (ISSUE 2 satellite): label values containing a
    backslash, a double-quote or a newline must render per the
    Prometheus text-format escaping rules — the pre-fix _fmt_labels
    emitted them raw, corrupting the whole scrape."""
    reg = Registry()
    c = reg.counter("iotml_poison_total")
    c.inc(1, path='a"b', note="back\\slash", multi="line1\nline2")
    text = reg.render()
    assert 'path="a\\"b"' in text
    assert 'note="back\\\\slash"' in text
    assert 'multi="line1\\nline2"' in text
    assert "\nline2" not in text  # no raw newline inside a label value
    # the sample line parses as `name{k="v",...} value` with only
    # escaped specials inside each quoted value
    import re

    sample = [ln for ln in text.splitlines()
              if ln.startswith("iotml_poison_total{")]
    assert len(sample) == 1
    label_val = r'"(?:[^"\\\n]|\\\\|\\"|\\n)*"'
    assert re.fullmatch(
        r"iotml_poison_total\{[a-z_]+=%s(?:,[a-z_]+=%s)*\} 1\.0"
        % (label_val, label_val), sample[0])


def test_labeled_histogram_series_render():
    """iotml_stage_seconds-style families: one bucket/sum/count series
    per label set, plus the unlabeled backward-compatible shape."""
    reg = Registry()
    h = reg.histogram("iotml_stage_seconds", "per-stage", buckets=(0.1, 1.0))
    h.observe(0.05, stage="decode")
    h.observe(0.5, stage="decode")
    h.observe(0.5, stage="score")
    text = reg.render()
    assert 'iotml_stage_seconds_bucket{le="0.1",stage="decode"} 1' in text
    assert 'iotml_stage_seconds_bucket{le="+Inf",stage="decode"} 2' in text
    assert 'iotml_stage_seconds_count{stage="decode"} 2' in text
    assert 'iotml_stage_seconds_count{stage="score"} 1' in text
    assert text.count("# TYPE iotml_stage_seconds histogram") == 1
    snap = reg.collect()
    assert snap['iotml_stage_seconds_count{stage="decode"}'] == 2.0
    # unlabeled histograms keep the exact legacy exposition shape
    reg2 = Registry()
    h2 = reg2.histogram("iotml_train_step_seconds", buckets=(0.1, 1.0))
    h2.observe(0.05)
    t2 = reg2.render()
    assert 'iotml_train_step_seconds_bucket{le="0.1"} 1' in t2
    assert "iotml_train_step_seconds_count 1" in t2
    assert reg2.collect()["iotml_train_step_seconds_count"] == 1.0


def test_metrics_http_server():
    reg = Registry()
    reg.counter("iotml_test_total").inc(3)
    srv = start_http_server(port=0, registry=reg)  # port 0 = ephemeral
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "iotml_test_total 3.0" in body
    finally:
        srv.shutdown()


def test_scalar_logger_jsonl(tmp_path):
    log = ScalarLogger(str(tmp_path), use_tensorboard=False)
    log.history({"loss": [0.5, 0.4], "accuracy": [0.0, 0.0],
                 "seconds": [1.0, 1.0]})
    log.close()
    rows = [json.loads(l) for l in open(tmp_path / "scalars.jsonl")]
    assert rows[0]["tag"] == "train/loss"
    assert rows[1]["value"] == 0.4
    assert {r["tag"] for r in rows} == {"train/loss", "train/accuracy",
                                        "train/epoch_seconds"}


def test_cli_train_predict_handoff(tmp_path):
    root = str(tmp_path / "store")
    rc = cardata_main(["emulator:11000", "SENSOR_DATA_S_AVRO", "0",
                       "model-predictions", "train", "m1", root])
    assert rc == 0
    rc = cardata_main(["emulator:21000", "SENSOR_DATA_S_AVRO", "0",
                       "model-predictions", "predict", "m1", root])
    assert rc == 0


def test_cli_arg_validation():
    assert cardata_main(["too", "few"]) == 1
    assert cardata_main(["emulator", "t", "0", "r", "badmode", "m", "/tmp/x"]) == 1


def test_profiler_trace_capture(tmp_path):
    """obs.profile writes TensorBoard-layout trace artifacts (the
    reference commits TF profiler traces; SURVEY §5)."""
    import jax.numpy as jnp

    from iotml.obs.profile import annotate, maybe_trace, trace, trace_files

    logdir = str(tmp_path / "logs")
    with trace(logdir):
        with annotate("tiny-op"):
            _ = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    files = trace_files(logdir)
    assert files, "no trace artifacts captured"
    assert any("plugins" in f and "profile" in f for f in files)

    # no-op path: nothing written, nothing raised
    with maybe_trace(None):
        pass
