"""iotml.online: drift detectors as pure units (seeded streams,
detection-delay and false-positive bounds), the incremental learner's
update/adapt/publish loop, the adversarial fleet conditions
(backpressure, flapping links, schema mix, regional drift), Avro
schema evolution through the consume paths, and the e2e
drift-adapt-swap loop against a live registry + watcher + scorer."""

import dataclasses
import tempfile

import numpy as np
import pytest

from iotml.online.detectors import (ADAPTING, STABLE, AdaptiveWindow,
                                    DriftMonitor, PageHinkley)

SEED = 7


def _stream(mean, std, n, rng):
    return rng.normal(mean, std, n)


# ------------------------------------------------------------ detectors
def test_page_hinkley_step_drift_delay_and_no_false_positives():
    rng = np.random.default_rng(SEED)
    xs = np.concatenate([_stream(0.02, 0.004, 400, rng),
                         _stream(0.08, 0.008, 400, rng)])
    ph = PageHinkley(delta=0.005, threshold=0.1)
    fired = next((i for i, x in enumerate(xs) if ph.update(x)), None)
    assert fired is not None and 400 <= fired <= 425  # <= 25 obs delay
    # stationary stream: zero false positives over 5k observations
    ph2 = PageHinkley(delta=0.005, threshold=0.1)
    assert not any(ph2.update(x)
                   for x in _stream(0.02, 0.004, 5000, rng))


def test_page_hinkley_ramp_drift_fires():
    rng = np.random.default_rng(SEED)
    ramp = np.concatenate([
        _stream(0.02, 0.004, 300, rng),
        0.02 + np.linspace(0, 0.06, 400) + rng.normal(0, 0.004, 400)])
    ph = PageHinkley(delta=0.005, threshold=0.1)
    fired = next((i for i, x in enumerate(ramp) if ph.update(x)), None)
    assert fired is not None and fired < 450  # inside the ramp's front


def test_adwin_step_drift_cuts_to_post_drift_window():
    rng = np.random.default_rng(SEED)
    xs = np.concatenate([_stream(0.02, 0.004, 300, rng),
                         _stream(0.08, 0.008, 300, rng)])
    aw = AdaptiveWindow(delta=0.002)
    fired = [i for i, x in enumerate(xs) if aw.update(x)]
    assert fired and fired[0] >= 300  # never inside the pre-drift half
    # the adaptive window dropped the old regime: its mean is the NEW
    # distribution's, and its width is (well) under the full stream
    assert abs(aw.mean - 0.08) < 0.01
    assert aw.width < 450
    # stationary: no cuts, bounded sketch state
    aw2 = AdaptiveWindow(delta=0.002)
    assert not any(aw2.update(x)
                   for x in _stream(0.02, 0.004, 5000, rng))
    n_buckets = sum(len(row) for row in aw2._rows)
    assert aw2.width == 5000 and n_buckets <= 80  # O(log n) compression


def test_monitor_step_detect_converge_reanchor():
    rng = np.random.default_rng(SEED)
    mon = DriftMonitor()
    events = []
    for i, x in enumerate(np.concatenate(
            [_stream(0.02, 0.004, 300, rng),
             _stream(0.08, 0.008, 60, rng)])):
        s = mon.update(x)
        if s:
            events.append((i, s))
    assert len(events) == 1 and events[0][0] <= 310  # <= 10-obs delay
    assert mon.state == ADAPTING
    # "adaptation" heals the signal back toward baseline: converge and
    # re-anchor (the new normal), detectors re-armed
    for x in _stream(0.025, 0.004, 200, rng):
        mon.update(x)
    assert mon.state == STABLE and mon.converged == 1
    assert 0.02 < mon.baseline < 0.04


def test_monitor_no_false_positives_and_tracks_improvement():
    # a TRAINING model's error declines; the baseline must follow it
    # down so neither the decline nor the noise fires
    rng = np.random.default_rng(SEED)
    mon = DriftMonitor()
    declining = 0.4 * np.exp(-np.arange(2000) / 400.0) + \
        rng.normal(0, 0.01, 2000) + 0.1
    assert not any(mon.update(x) for x in declining)
    assert mon.baseline < 0.15  # followed the improvement down


def test_monitor_level_rule_catches_self_healing_excursion():
    # an excursion that PH's running mean absorbs (slow rise to +40%
    # then the learner heals it) must still fire via the level rule
    rng = np.random.default_rng(SEED)
    mon = DriftMonitor(detector="both", ph_threshold=50.0)  # PH muted
    for x in _stream(0.10, 0.005, 100, rng):
        mon.update(x)
    fired = [mon.update(x)
             for x in _stream(0.14, 0.005, 40, rng)]
    sigs = [s for s in fired if s]
    assert sigs and sigs[0] == "level"


def test_monitor_severity_and_window_reset():
    mon = DriftMonitor()
    for x in [0.1] * 50:
        mon.update(x)
    assert mon.severity() == pytest.approx(1.0, abs=0.05)
    mon.ph._cum = 5.0
    mon.adwin.update(1.0)
    mon.reset_windows()
    assert mon.ph.stat == 0.0 and mon.adwin.width == 0


# ----------------------------------------------------- fleet conditions
def _mk_fleet(cond_name, cars=25, seed=SEED, **overrides):
    from iotml.gen.scenarios import AdversarialFleet, condition
    from iotml.gen.simulator import FleetScenario

    return AdversarialFleet(
        FleetScenario(num_cars=cars, failure_rate=0.0, seed=seed),
        condition(cond_name, **overrides))


def test_condition_lookup_and_override():
    from iotml.gen.scenarios import FLEET_CONDITIONS, condition

    c = condition("regional-drift", drift_tick=40)
    assert c.drift_tick == 40 and c.regions == 4
    assert FLEET_CONDITIONS["regional-drift"].drift_tick is None
    with pytest.raises(KeyError):
        condition("nope")


def test_regional_drift_shifts_only_drifted_cohorts():
    fleet = _mk_fleet("regional-drift", drift_tick=5, drift_regions=(1,))
    pre = [fleet.step_columns() for _ in range(5)]
    post = [fleet.step_columns() for _ in range(5)]

    def mean_by_region(colss, col, region):
        sel = np.concatenate(
            [c[col][fleet.region[c["car"]] == region] for c in colss])
        return float(sel.mean())

    # region 1 moved (tire_pressure_2_1 shifts by -10 per unit);
    # region 0 stayed inside its static-skew band
    d1 = mean_by_region(post, "tire_pressure_2_1", 1) \
        - mean_by_region(pre, "tire_pressure_2_1", 1)
    d0 = mean_by_region(post, "tire_pressure_2_1", 0) \
        - mean_by_region(pre, "tire_pressure_2_1", 0)
    assert d1 < -5 and abs(d0) < 3
    # labels untouched: drift is NOT failure
    assert all((c["failure_occurred"] == "false").all() for c in post)


def test_rush_hour_burst_multiplies_published_records():
    from iotml.stream.broker import Broker

    fleet = _mk_fleet("rush-hour")  # burst ticks [4, 8) at 10x
    b = Broker()
    quiet = fleet.publish_stream(b, "T", n_ticks=4)   # ticks 0-3
    burst = fleet.publish_stream(b, "T", n_ticks=1)   # tick 4: 10x
    assert quiet == 4 * 25 and burst == 10 * 25


def test_flapping_links_store_and_forward():
    from iotml.mqtt.broker import MqttBroker

    fleet = _mk_fleet("flapping-links", cars=50)
    mqtt = MqttBroker()
    got = []
    s = mqtt.connect("sink", lambda t, p, q, r: got.append(p))
    mqtt.deliver_pending(s)
    mqtt.subscribe("sink", "vehicles/sensor/data/#")
    delivered = fleet.publish_mqtt(mqtt, n_ticks=30)
    assert fleet.flap_buffered_total > 0          # links really flapped
    assert delivered == len(got)
    # store-and-forward: most buffered readings drained on recovery
    # (steady-state down fraction ~0.19 at these flap rates), and the
    # undelivered remainder is sitting in bounded per-car buffers —
    # deferred/buffered, not silently dropped
    pending = sum(len(d) for d in fleet._car_buffers.values())
    assert delivered >= 1000
    assert delivered + pending <= 30 * 50


def test_backpressure_signal_defers_instead_of_drop_oldest():
    from iotml.mqtt.broker import MqttBroker
    from iotml.obs.metrics import default_registry

    # a RECONNECTING persistent session (pending backlog) with a tiny
    # queue bound: without backpressure the broker drop-oldests
    mqtt = MqttBroker(offline_queue_limit=100, backpressure_hwm=40)
    mqtt.connect("slow", lambda *a: None, clean_start=False)
    mqtt.subscribe("slow", "vehicles/sensor/data/#")
    mqtt.disconnect("slow")
    session = mqtt.connect("slow", lambda *a: None, clean_start=False)
    # session.pending stays buffered until deliver_pending: the
    # "reconnect in progress" window the burst lands in
    fleet = _mk_fleet("rush-hour", cars=25)
    ctr = default_registry.counter("iotml_mqtt_backpressure_total")
    before = ctr.value()
    sent = fleet.publish_mqtt(mqtt, n_ticks=8)  # includes the 10x burst
    assert mqtt.saturated()
    assert ctr.value() > before                  # counter moved
    assert fleet.deferred_total > 0              # agents really deferred
    # the broker queue stayed at/near the high-water mark — drop-oldest
    # never engaged (queue below the hard limit)
    assert len(session.pending) < 100
    # drain the receiver: signal clears, deferred records flow through
    mqtt.deliver_pending(session)
    assert not mqtt.saturated()
    assert sent > 0
    fleet.publish_mqtt(mqtt, n_ticks=1)
    assert len(fleet.deferred) < fleet.deferred_total  # backlog shrank


# ------------------------------------------------------ schema evolution
def test_mixed_schema_topic_resolves_through_sensor_batches():
    from iotml.core.schema import CAR_SCHEMA_V2_ID
    from iotml.data.dataset import SensorBatches
    from iotml.ops.framing import unframe
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    fleet = _mk_fleet("schema-mix", cars=25)
    b = Broker()
    n = fleet.publish_stream(b, "T", n_ticks=8)
    # both writer ids actually landed on the topic
    ids = {unframe(m.value)[0] for m in b.fetch("T", 0, 0, 10_000)}
    assert ids == {1, CAR_SCHEMA_V2_ID}
    sb = SensorBatches(StreamConsumer(b, ["T:0:0"]), batch_size=50)
    batches = list(sb)
    assert sum(x.n_valid for x in batches) == n
    assert batches[0].x.shape == (50, 18)  # reader-schema width


def test_mixed_schema_topic_resolves_through_sql_decode():
    from iotml.stream.broker import Broker
    from iotml.streamproc.sql import SqlEngine, install_reference_pipeline

    fleet = _mk_fleet("schema-mix", cars=25)
    b = Broker()
    b.create_topic("sensor-data")  # the DDL's JSON leg (unused here)
    n = fleet.publish_stream(b, "SENSOR_DATA_S_AVRO", n_ticks=4,
                             partitions=1)
    eng = SqlEngine(b)
    install_reference_pipeline(eng)
    # a SELECT decodes every record through the engine's AVRO source:
    # v2-framed rows must resolve by name, not mis-read positionally
    rows = eng.execute("SELECT SPEED, FAILURE_OCCURRED "
                       "FROM SENSOR_DATA_S_AVRO;")[0]["rows"]
    assert len(rows) == n  # nothing dead-lettered / dropped
    labels = {r[1] for r in rows}
    assert labels <= {"true", "false"}  # never a REGION string leaked
    assert all(isinstance(r[0], float) for r in rows)


def test_json_to_avro_v2_writer_and_v1_reader_interop():
    import json as _json

    from iotml.core.schema import KSQL_CAR_SCHEMA_V2
    from iotml.ops.avro import AvroCodec, ResolvingCodec
    from iotml.ops.framing import unframe
    from iotml.stream.broker import Broker
    from iotml.streamproc.tasks import JsonToAvro

    b = Broker()
    b.create_topic("sensor-data")
    rec = {"speed": 12.5, "coolant_temp": 40.0, "region": "region-2",
           "failure_occurred": "false"}
    b.produce("sensor-data", _json.dumps(rec).encode(), key=b"car-1")
    task = JsonToAvro(b, schema_version=2, dst="OUT_V2")
    task.process_available()
    from iotml.core.schema import CAR_SCHEMA_V2_ID

    msg = b.fetch("OUT_V2", 0, 0, 10)[0]
    sid, payload = unframe(msg.value)
    assert sid == CAR_SCHEMA_V2_ID
    v2 = AvroCodec(KSQL_CAR_SCHEMA_V2).decode(payload)
    assert v2["REGION"] == "region-2" and v2["SPEED"] == 12.5
    # the v1 reader resolves the same bytes (REGION dropped by name)
    from iotml.core.schema import KSQL_CAR_SCHEMA

    v1 = ResolvingCodec(KSQL_CAR_SCHEMA).decode_framed(msg.value)
    assert "REGION" not in v1 and v1["SPEED"] == 12.5
    assert v1["FAILURE_OCCURRED"] == "false"


# --------------------------------------------------------------- learner
def _learner(broker, topic, **kw):
    from iotml.online.learner import OnlineLearner

    kw.setdefault("window", 50)
    kw.setdefault("publish_every", 10**9)
    return OnlineLearner(broker, topic, **kw)


def test_learner_lr_boost_is_runtime_mutable():
    from iotml.stream.broker import Broker

    b = Broker()
    fleet = _mk_fleet("baseline")
    fleet.publish_stream(b, "T", n_ticks=4)
    lrn = _learner(b, "T")
    assert lrn.process_available() > 0
    assert lrn.current_lr == pytest.approx(1e-3)
    lrn.set_lr(5e-3)
    assert lrn.current_lr == pytest.approx(5e-3)
    fleet.publish_stream(b, "T", n_ticks=2)
    assert lrn.process_available() > 0  # same compiled step, boosted
    assert np.isfinite(lrn.last_loss)


def test_learner_bounded_drains_lose_no_rows():
    from iotml.stream.broker import Broker

    b = Broker()
    fleet = _mk_fleet("baseline")
    n = fleet.publish_stream(b, "T", n_ticks=13)  # 325: not window-even
    lrn = _learner(b, "T", only_normal=False)
    # bounded drains are take-budgeted: the batcher never polls past
    # what a drain will train, so no row is skipped across calls AND
    # the consumer cursor never runs ahead of the trained frontier
    # (the offsets-as-checkpoint edge)
    total = 0
    while True:
        got = lrn.process_available(max_updates=2)
        if not got:
            break
        total += got
        for _t, _p, off in lrn.consumer.positions():
            assert off <= lrn.records_trained + lrn.window
    assert lrn.records_trained == n


def test_learner_detects_and_adapts_on_regional_drift():
    from iotml.stream.broker import Broker

    b = Broker()
    fleet = _mk_fleet("regional-drift", cars=25, drift_tick=80)
    lrn = _learner(b, "T")
    fleet.publish_stream(b, "T", n_ticks=80)
    lrn.process_available()
    assert lrn.monitor.drifts == 0  # stationary phase: no false fire
    fleet.publish_stream(b, "T", n_ticks=120)
    lrn.process_available()
    assert lrn.monitor.drifts >= 1
    assert lrn.adaptations and lrn.adaptations[0][2] in ("boost",
                                                         "refit")
    # detection delay: within 20 windows (1000 records) of onset
    assert lrn.adaptations[0][0] - 80 <= 20
    assert lrn.monitor.converged >= 1  # healed by stream end


def test_learner_publishes_through_registry_commit_trails_manifest():
    from iotml.mlops import ModelRegistry
    from iotml.stream.broker import Broker

    b = Broker()
    fleet = _mk_fleet("baseline")
    root = tempfile.mkdtemp()
    reg = ModelRegistry(root)
    lrn = _learner(b, "T", registry=reg, publish_every=4,
                   group="online-test")
    fleet.publish_stream(b, "T", n_ticks=20)
    lrn.process_available()
    versions = lrn.write_published()
    assert versions, "publish cadence produced no versions"
    m = reg.manifest(reg.latest())
    assert m.metrics.get("online") == 1.0
    committed = b.committed("online-test", "T", 0)
    stamped = {p: off for _t, p, off in m.offsets}[0]
    assert committed == stamped  # group commit trails manifest exactly
    # a second incarnation resumes model + cursor as one unit
    lrn2 = _learner(b, "T", registry=reg, group="online-test")
    assert lrn2.restored_version == reg.latest()
    assert lrn2.consumer.positions()[0][2] == stamped


def test_drift_adapt_swap_e2e():
    """The tentpole loop, compact: drift → detect → adapt → publish →
    RegistryWatcher hot-swaps the scorer → nothing lost or doubled."""
    from iotml.data.dataset import SensorBatches
    from iotml.mlops import ModelRegistry, RegistryWatcher
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence

    b = Broker()
    fleet = _mk_fleet("regional-drift", cars=25, drift_tick=80)
    reg = ModelRegistry(tempfile.mkdtemp())
    lrn = _learner(b, "T", registry=reg, publish_every=20)
    consumer = StreamConsumer(b, ["T:0:0"], group="swap-scorer")
    scorer = StreamScorer(
        CAR_AUTOENCODER, None,
        SensorBatches(consumer, batch_size=50),
        OutputSequence(b, "preds", partition=0))
    watcher = RegistryWatcher(reg, scorers=[scorer])

    published = 0
    for phase_ticks in (80, 120):
        published += fleet.publish_stream(b, "T", n_ticks=phase_ticks)
        while lrn.process_available(max_updates=10):
            lrn.write_published()
            watcher.poll_once()
            if watcher.current_version is not None:
                # no model, no scoring: the watcher's wait_for_model
                # contract, inlined for the deterministic drive
                scorer.score_available(max_rows=1000)
        scorer.score_available()
    assert lrn.monitor.drifts >= 1 and lrn.adaptations
    latest = reg.latest()
    assert latest is not None and watcher.swaps >= 1
    assert scorer.model_version == latest
    # zero lost, zero double-scored across every swap
    assert scorer.scored == published
    assert b.end_offset("preds", 0) == published


def test_drift_storm_schedule_is_deterministic():
    from iotml.chaos import scenarios

    a = scenarios.build("drift-storm", seed=11, records=1000)
    bb = scenarios.build("drift-storm", seed=11, records=1000)
    assert a.text() == bb.text()
    assert a.topology == "online"
    assert any(e.point == "mqtt.deliver" and e.action == "drop"
               for e in a.events)


def test_online_config_env_round_trip():
    from iotml.config import load_config

    cfg, _ = load_config([], env={"IOTML_ONLINE_WINDOW": "200",
                                  "IOTML_ONLINE_PH_DELTA": "0.2",
                                  "IOTML_ONLINE_DETECTOR": "adwin"})
    assert cfg.online.window == 200
    assert cfg.online.ph_delta == pytest.approx(0.2)
    assert cfg.online.detector == "adwin"
    with pytest.raises(ValueError):
        load_config([], env={"IOTML_ONLINE_WIDNOW": "1"})  # typo fails
