"""Mesh sharding on the 8-virtual-device CPU mesh (SURVEY §4 rebuild impl c)."""

import jax
import numpy as np
import pytest

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.parallel.data_parallel import ShardedTrainer, param_specs, shard_params
from iotml.parallel.distributed import assign_partitions, consumer_specs
from iotml.parallel.mesh import auto_mesh, make_mesh, batch_sharding
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer


def test_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_auto_mesh_shapes():
    mesh = auto_mesh()
    assert mesh.shape == {"data": 8, "model": 1}
    mesh = auto_mesh(model_parallel=2)
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh((3, 2), ("a", "b"))


def test_batch_sharding_splits_rows():
    mesh = auto_mesh()
    x = np.zeros((64, 18), np.float32)
    xd = jax.device_put(x, batch_sharding(mesh))
    assert len(xd.addressable_shards) == 8
    assert xd.addressable_shards[0].data.shape == (8, 18)


def test_param_specs_tensor_parallel_hook():
    mesh = auto_mesh(model_parallel=2)
    params = CAR_AUTOENCODER.init(jax.random.PRNGKey(0),
                                  np.zeros((1, 18), np.float32))["params"]
    specs = param_specs(params, mesh)
    # encoder0 kernel [18,14]: 14 % 2 == 0 → sharded over model axis
    assert specs["encoder0"]["kernel"] == jax.sharding.PartitionSpec(None, "model")
    # encoder1 kernel [14,7]: 7 % 2 != 0 → replicated
    assert specs["encoder1"]["kernel"] == jax.sharding.PartitionSpec()
    sharded = shard_params(params, mesh)
    assert sharded["encoder0"]["kernel"].sharding.spec == specs["encoder0"]["kernel"]


def _stream_batches(num_cars=64, ticks=10, batch_size=64):
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=num_cars, failure_rate=0.0))
    gen.publish(broker, "s", n_ticks=ticks)
    consumer = StreamConsumer(broker, ["s:0:0"])
    return SensorBatches(consumer, batch_size=batch_size, only_normal=True)


def test_sharded_trainer_dp_matches_single_chip():
    """DP over 8 devices must be numerically equivalent to single-device."""
    from iotml.train.loop import Trainer

    batches = _stream_batches()
    ref_batches = _stream_batches()

    mesh = auto_mesh()
    st = ShardedTrainer(CAR_AUTOENCODER, mesh)
    hist_dp = st.fit(batches, epochs=2)

    tr = Trainer(CAR_AUTOENCODER)
    hist_ref = tr.fit(ref_batches, epochs=2)

    np.testing.assert_allclose(hist_dp["loss"], hist_ref["loss"],
                               rtol=1e-4, atol=1e-6)
    # params agree too
    for a, b in zip(jax.tree.leaves(jax.device_get(st.state.params)),
                    jax.tree.leaves(jax.device_get(tr.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_sharded_trainer_with_model_axis_runs():
    mesh = auto_mesh(model_parallel=2)
    st = ShardedTrainer(CAR_AUTOENCODER, mesh)
    hist = st.fit(_stream_batches(ticks=4), epochs=1)
    assert np.isfinite(hist["loss"]).all()


def test_partition_assignment():
    # 10 partitions over 4 hosts (reference: 10-partition topics)
    seen = []
    for h in range(4):
        ps = assign_partitions(10, 4, h)
        seen.extend(ps)
        assert ps == sorted(ps)
    assert sorted(seen) == list(range(10))
    assert consumer_specs("sensor-data", [0, 4], offset=7) == \
        ["sensor-data:0:7", "sensor-data:4:7"]
