"""Pipeline parallelism on the virtual 8-device mesh: the staged schedule
must match unstaged sequential application, and the pp train step must match
the single-device dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from iotml.models.transformer import SensorFormer
from iotml.parallel.mesh import make_mesh
from iotml.parallel.pipeline import (make_pp_train_step, pipeline_apply,
                                     stack_blocks, unstack_blocks)


def _mlp_stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_mlp(n_layers, dim, seed=0):
    r = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(r.normal(scale=0.3, size=(n_layers, dim, dim)),
                         jnp.float32),
        "b": jnp.asarray(r.normal(scale=0.1, size=(n_layers, dim)),
                         jnp.float32),
    }


def _sequential(stacked, x):
    for i in range(stacked["w"].shape[0]):
        x = _mlp_stage(jax.tree.map(lambda a, i=i: a[i], stacked), x)
    return x


def test_pipeline_apply_matches_sequential():
    mesh = make_mesh((4,), ("pipe",), devices=jax.devices()[:4])

    def stage_fn(local, h):
        # local leaves [layers_per_stage, ...]
        for j in range(local["w"].shape[0]):
            h = _mlp_stage(jax.tree.map(lambda a, j=j: a[j], local), h)
        return h

    stacked = _stacked_mlp(8, 16)  # 2 layers per stage
    mbs = jnp.asarray(
        np.random.default_rng(1).normal(size=(6, 5, 16)), jnp.float32)

    got = pipeline_apply(stage_fn, mesh)(stacked, mbs)
    want = jax.vmap(lambda m: _sequential(stacked, m))(mbs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_apply_grads_match_sequential():
    mesh = make_mesh((4,), ("pipe",), devices=jax.devices()[:4])

    def stage_fn(local, h):
        for j in range(local["w"].shape[0]):
            h = _mlp_stage(jax.tree.map(lambda a, j=j: a[j], local), h)
        return h

    stacked = _stacked_mlp(4, 8, seed=2)
    mbs = jnp.asarray(
        np.random.default_rng(3).normal(size=(4, 3, 8)), jnp.float32)

    piped = pipeline_apply(stage_fn, mesh)

    def loss_p(p):
        return jnp.mean(jnp.square(piped(p, mbs)))

    def loss_s(p):
        return jnp.mean(jnp.square(
            jax.vmap(lambda m: _sequential(p, m))(mbs)))

    gp = jax.grad(loss_p)(stacked)
    gs = jax.grad(loss_s)(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   rtol=1e-4, atol=1e-5)


def test_stack_unstack_roundtrip():
    model = SensorFormer(features=6, d_model=16, num_heads=2, num_layers=4)
    x = jnp.zeros((2, 8, 6), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    static, blocks = stack_blocks(params, 4)
    back = unstack_blocks(static, blocks, 4)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, back)


def test_pp_train_step_matches_dense_oracle():
    mesh = make_mesh((2, 4), ("data", "pipe"))
    model = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=4)
    tx = optax.adam(1e-3)
    init, step, put_x = make_pp_train_step(model, tx, mesh, n_microbatches=2)

    x = np.random.default_rng(0).normal(size=(8, 16, 18)).astype(np.float32)
    state = init(jax.random.PRNGKey(0), x)

    # oracle: same params, plain dense apply on one device
    raw = unstack_blocks(state.params["static"],
                         jax.device_get(state.params["blocks"]), 4)
    pred = model.apply({"params": raw}, jnp.asarray(x))
    want = float(jnp.mean(jnp.square(pred[:, :-1] - x[:, 1:])))

    state, m = step(state, put_x(x))
    got = float(jax.device_get(m["loss"]))
    np.testing.assert_allclose(got, want, rtol=1e-4)

    # loss decreases over a few steps — the update is real
    losses = [got]
    for _ in range(4):
        state, m = step(state, put_x(x))
        losses.append(float(jax.device_get(m["loss"])))
    assert losses[-1] < losses[0]


def test_pp_blocks_stay_sharded_over_pipe():
    mesh = make_mesh((1, 8), ("data", "pipe"))
    model = SensorFormer(features=6, d_model=16, num_heads=2, num_layers=8)
    init, step, put_x = make_pp_train_step(
        model, optax.sgd(1e-2), mesh, n_microbatches=2)
    x = np.random.default_rng(1).normal(size=(4, 8, 6)).astype(np.float32)
    state = init(jax.random.PRNGKey(0), x)
    state, _ = step(state, put_x(x))
    kern = state.params["blocks"]["attn"]["qkv"]["kernel"]
    shards = kern.sharding.shard_shape(kern.shape)
    assert shards[0] == 1  # 8 layers over 8 pipe devices
