"""Full-platform bring-up: every reference service in one process, every
interaction over its real network surface (MQTT TCP, Kafka wire TCP, three
REST APIs) — the `terraform apply`-to-first-record path of SURVEY §3.5,
minus the Kubernetes cluster."""

import http.client
import json
import time

import pytest

from iotml.cli.up import Platform


@pytest.fixture
def platform():
    p = Platform(partitions=4).start()
    yield p
    p.stop()


def _get(url_host, port, path):
    conn = http.client.HTTPConnection(url_host, port, timeout=5)
    conn.request("GET", path)
    r = conn.getresponse()
    return r.status, json.loads(r.read())


def test_mqtt_to_ksql_to_training_over_real_sockets(platform):
    """Device → MQTT TCP → bridge → sensor-data → KSQL pipeline → framed
    Avro → training batches: the reference's L1→L5 ingest path end-to-end."""
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.mqtt.wire import MqttClient
    from iotml.stream.consumer import StreamConsumer

    scenario = FleetScenario(num_cars=8, failure_rate=0.0)
    gen = FleetGenerator(scenario)
    clients = [MqttClient("127.0.0.1", platform.mqtt.port, scenario.car_id(i))
               for i in range(8)]
    for _ in range(40):
        cols = gen.step_columns()
        for i, c in enumerate(clients):
            rec = gen.row_record(cols, i, KSQL_CAR_SCHEMA)
            c.publish(f"vehicles/sensor/data/{scenario.car_id(i)}",
                      json.dumps(rec).encode(), qos=1)
    for c in clients:
        c.disconnect()

    deadline = time.time() + 10
    while platform.bridge.forwarded() < 320 and time.time() < deadline:
        time.sleep(0.05)
    assert platform.bridge.forwarded() == 320

    platform.pump()  # run the KSQL pipeline over what arrived

    spec = platform.broker.topic("SENSOR_DATA_S_AVRO")
    consumer = StreamConsumer(
        platform.broker,
        [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)],
        group="up-test")
    batches = SensorBatches(consumer, batch_size=64)
    batch = next(iter(batches))
    assert batch.x.shape == (64, 18)


def test_all_rest_surfaces_respond(platform):
    eps = platform.endpoints()

    host, port = eps["schema-registry"].rsplit(":", 1)[0].split("//")[1], \
        int(eps["schema-registry"].rsplit(":", 1)[1])
    status, subjects = _get(host, port, "/subjects")
    assert status == 200
    assert "sensor-data-value" in subjects
    assert "SENSOR_DATA_S_AVRO-value" in subjects

    host, port = eps["ksql"].split("//")[1].rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=5)
    conn.request("POST", "/ksql", json.dumps({"ksql": "SHOW QUERIES;"}),
                 {"Content-Type": "application/json"})
    queries = json.loads(conn.getresponse().read())[0]["queries"]
    assert len(queries) == 3  # the reference DDL's persistent queries

    host, port = eps["connect"].split("//")[1].rsplit(":", 1)
    status, plugins = _get(host, int(port), "/connector-plugins")
    assert status == 200 and len(plugins) == 3


def test_kafka_wire_port_serves_reference_topics(platform):
    from iotml.stream.kafka_wire import KafkaWireBroker

    client = KafkaWireBroker(f"127.0.0.1:{platform.kafka.port}")
    topics = client.topics()
    assert "sensor-data" in topics and "model-predictions" in topics
    assert client.topic("sensor-data").partitions == 4
    client.produce("model-predictions", b"[0.1 0.2]", key=b"car0")
    msgs = client.fetch("model-predictions", 0, 0)
    end = sum(client.end_offset("model-predictions", p) for p in range(4))
    assert end == 1
    client.close()


def test_platform_with_live_fleet():
    p = Platform(partitions=2).start()
    try:
        p.start_fleet(num_cars=5, rate_hz=20.0)
        deadline = time.time() + 10
        while p.bridge.forwarded() < 10 and time.time() < deadline:
            time.sleep(0.1)
        assert p.bridge.forwarded() >= 10
        p.pump()
        end = sum(p.broker.end_offset("SENSOR_DATA_S_AVRO", q)
                  for q in range(p.broker.topic("SENSOR_DATA_S_AVRO").partitions))
        assert end >= 10
    finally:
        p.stop()


def test_demo_end_to_end(capsys):
    """The one-command demo: fleet → bridge → KSQL → train → checkpoint →
    score → anomaly verdicts, all in-process."""
    import json as _json

    from iotml.cli import demo

    rc = demo.main(["--cars", "6", "--seconds", "2", "--rate", "20",
                    "--epochs", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    summary = _json.loads(out[out.index("{"):])
    assert summary["mqtt_messages_bridged"] > 0
    assert summary["ksql_avro_records"] == summary["mqtt_messages_bridged"]
    assert summary["scored"] == summary["ksql_avro_records"]
    assert summary["loss_first_to_last"][1] <= summary["loss_first_to_last"][0]


def test_control_center_ui_and_status(platform):
    eps = platform.endpoints()
    host, port = eps["control-center"].split("//")[1].rsplit(":", 1)
    status, snap = _get(host, int(port), "/api/status")
    assert status == 200
    assert any(t["name"] == "sensor-data" for t in snap["topics"])
    assert len(snap["ksql"]["queries"]) == 3
    assert "mqtt_sessions" in snap and "metrics" in snap

    conn = http.client.HTTPConnection(host, int(port), timeout=5)
    conn.request("GET", "/")
    r = conn.getresponse()
    page = r.read().decode()
    assert r.status == 200 and "iotml control center" in page
    assert "sensor-data" in page


def test_car_health_twin_loop(platform):
    """VERDICT-r4 #4, the digital-twin loop closed: a car flips to ALERT
    on the car-health feed, the platform's DocumentStoreSink (the
    reference's MongoDB twin) upserts it by car id, a point lookup
    returns the car's latest state, and the control center surfaces the
    active alert."""
    import numpy as np

    from iotml.serve.carhealth import CarHealthDetector

    det = CarHealthDetector(threshold=0.5, alpha=1.0, min_records=1)
    car = b"electric-vehicle-00042"
    trans = det.update(np.array([car], "S32"), np.array([9.0]))
    assert det.publish_transitions(platform.broker, "car-health",
                                   trans) == 1
    platform.pump()  # drive the connect worker deterministically

    doc = platform.car_twin.find_one(car.decode())
    assert doc is not None and doc["state"] == "ALERT"
    assert doc["car"] == car.decode() and doc["ema"] == 9.0

    snap = platform.control_center.snapshot()
    ch = snap["car_health"]
    assert ch["n_active"] == 1
    assert ch["active_alerts"][0]["car"] == car.decode()

    # recovery flows through too: CLEAR upserts over the ALERT
    cleared = []
    while not cleared:
        cleared = det.update(np.array([car], "S32"), np.array([0.0]))
    det.publish_transitions(platform.broker, "car-health", cleared)
    platform.pump()
    assert platform.car_twin.find_one(car.decode())["state"] == "CLEAR"
    assert platform.control_center.snapshot()["car_health"]["n_active"] == 0
    # the twin connector is visible on the Connect REST surface
    assert "car-health-twin" in platform.connect._configs
