"""Device prefetcher: overlap, early-abandon, single-use contract."""

import numpy as np
import pytest

from iotml.data.dataset import Batch
from iotml.data.prefetch import DevicePrefetcher


def _batches(n=6, b=16):
    for i in range(n):
        yield Batch(x=np.full((b, 18), float(i), np.float32), n_valid=b,
                    first_index=i * b)


def test_prefetch_delivers_all_in_order():
    got = []
    for (x, y, mask), b in DevicePrefetcher(_batches(6)):
        assert y is None
        assert int(np.asarray(mask).sum()) == b.n_valid
        got.append((float(np.asarray(x)[0, 0]), b.first_index))
    assert got == [(float(i), i * 16) for i in range(6)]


def test_prefetch_propagates_source_error():
    def bad():
        yield from _batches(2)
        raise RuntimeError("stream died")

    pf = DevicePrefetcher(bad())
    it = iter(pf)
    next(it), next(it)
    with pytest.raises(RuntimeError, match="stream died"):
        next(it)


def test_prefetch_early_break_releases_worker():
    pf = DevicePrefetcher(_batches(100), depth=2)
    for i, item in enumerate(pf):
        if i == 1:
            break
    # worker must terminate rather than block on q.put forever
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_prefetch_is_single_use():
    pf = DevicePrefetcher(_batches(2))
    list(pf)
    with pytest.raises(RuntimeError, match="single-use"):
        list(pf)


def test_depth_below_one_rejected():
    import pytest

    from iotml.data.prefetch import DevicePrefetcher

    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher([], depth=0)
