"""MQTT QoS 2 exactly-once — PUBREC/PUBREL/PUBCOMP state machine.

The reference broker advertises maxQos 2
(reference `infrastructure/hivemq/hivemq-crd.yaml:13`); round 1 silently
downgraded QoS 2 subscriptions to 1.  These tests pin the full spec §4.3.3
flow on both TCP fronts: duplicate PUBLISH replay, reconnect mid-handshake
with a persistent session, and no-duplication through the Kafka bridge."""

import socket
import struct
import threading
import time

import pytest

from iotml.mqtt.bridge import KafkaBridge
from iotml.mqtt.broker import MqttBroker
from iotml.mqtt.eventserver import MqttEventServer
from iotml.mqtt.wire import (CONNACK, PUBCOMP, PUBREC, PUBREL, MqttClient,
                             MqttServer, connect_packet, packet,
                             publish_packet)
from iotml.stream.broker import Broker


def _recv_packet(sock):
    """Read one MQTT packet (small frames only) from a raw socket."""
    h = sock.recv(1)
    if not h:
        return None, b""
    (length,) = sock.recv(1)
    body = b""
    while len(body) < length:
        chunk = sock.recv(length - len(body))
        if not chunk:
            break
        body += chunk
    return h[0], body


def _raw_connect(port, client_id, clean=True):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.settimeout(10)
    s.sendall(connect_packet(client_id, clean=clean))
    h, _ = _recv_packet(s)
    assert h >> 4 == CONNACK
    return s


@pytest.mark.parametrize("server_cls", [MqttServer, MqttEventServer])
def test_qos2_end_to_end_both_fronts(server_cls):
    """Full QoS 2 pub → broker → QoS 2 sub delivery with both handshakes."""
    broker = MqttBroker()
    with server_cls(broker) as srv:
        got = []
        done = threading.Event()

        def on_msg(topic, payload):
            got.append((topic, payload))
            done.set()

        sub = MqttClient("127.0.0.1", srv.port, "sub2", on_message=on_msg)
        sub.subscribe("exact/#", qos=2)
        pub = MqttClient("127.0.0.1", srv.port, "pub2")
        pub.publish("exact/once", b"only-once", qos=2)  # blocks thru PUBCOMP
        assert done.wait(5)
        assert got == [("exact/once", b"only-once")]
        pub.disconnect()
        sub.disconnect()


def test_qos2_subscribe_granted_2():
    broker = MqttBroker()
    assert broker.subscribe("c", "a/#", qos=2) == 2


def test_duplicate_publish_replay_forwards_once():
    """A retried QoS 2 PUBLISH (same pid, DUP set — PUBREC was 'lost') is
    re-acknowledged but NOT re-forwarded."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        sub = MqttClient("127.0.0.1", srv.port, "watcher",
                         on_message=lambda t, p: got.append(p))
        sub.subscribe("exact/#", qos=0)

        s = _raw_connect(srv.port, "replayer")
        pub_pkt = publish_packet("exact/x", b"payload", qos=2, packet_id=77)
        s.sendall(pub_pkt)
        h, body = _recv_packet(s)
        assert h >> 4 == PUBREC and struct.unpack(">H", body)[0] == 77
        # replay the same pid WITHOUT releasing (simulates lost PUBREC)
        s.sendall(publish_packet("exact/x", b"payload", qos=2,
                                 packet_id=77, dup=True))
        h, body = _recv_packet(s)
        assert h >> 4 == PUBREC and struct.unpack(">H", body)[0] == 77
        # release completes the handshake
        s.sendall(packet(PUBREL, 0x02, struct.pack(">H", 77)))
        h, body = _recv_packet(s)
        assert h >> 4 == PUBCOMP
        time.sleep(0.2)
        assert got == [b"payload"], "duplicate must not be re-forwarded"
        # after PUBREL the id is reusable: a NEW publish with pid 77 flows
        s.sendall(publish_packet("exact/x", b"second", qos=2, packet_id=77))
        h, _ = _recv_packet(s)
        assert h >> 4 == PUBREC
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert got == [b"payload", b"second"]
        s.close()
        sub.disconnect()


def test_reconnect_mid_handshake_persistent_dedup():
    """Publisher gets PUBREC, dies before PUBREL, reconnects (persistent
    session) and retries the PUBLISH with DUP: the broker must not forward
    it again, and the late PUBREL still completes cleanly."""
    broker = MqttBroker()
    with MqttServer(broker) as srv:
        got = []
        sub = MqttClient("127.0.0.1", srv.port, "watcher",
                         on_message=lambda t, p: got.append(p))
        sub.subscribe("exact/#", qos=0)

        s1 = _raw_connect(srv.port, "flaky", clean=False)
        s1.sendall(publish_packet("exact/x", b"v", qos=2, packet_id=9))
        h, _ = _recv_packet(s1)
        assert h >> 4 == PUBREC
        s1.close()  # dies mid-handshake, no PUBREL

        s2 = _raw_connect(srv.port, "flaky", clean=False)
        # retry: same packet id, DUP
        s2.sendall(publish_packet("exact/x", b"v", qos=2, packet_id=9,
                                  dup=True))
        h, body = _recv_packet(s2)
        assert h >> 4 == PUBREC
        s2.sendall(packet(PUBREL, 0x02, struct.pack(">H", 9)))
        h, _ = _recv_packet(s2)
        assert h >> 4 == PUBCOMP
        time.sleep(0.2)
        assert got == [b"v"], "reconnect retry must not duplicate delivery"
        s2.close()
        sub.disconnect()


def test_qos2_no_duplicates_through_bridge():
    """The L2→L3 guarantee: a replayed QoS 2 PUBLISH reaches the stream
    broker exactly once."""
    mqtt_broker = MqttBroker()
    stream = Broker()
    bridge = KafkaBridge(mqtt_broker, stream, partitions=2)
    with MqttEventServer(mqtt_broker) as srv:
        s = _raw_connect(srv.port, "car-1", clean=False)
        pkt = publish_packet("vehicles/sensor/data/car-1", b"{\"v\":1}",
                             qos=2, packet_id=3)
        s.sendall(pkt)
        h, _ = _recv_packet(s)
        assert h >> 4 == PUBREC
        # replay twice more before releasing
        s.sendall(publish_packet("vehicles/sensor/data/car-1", b"{\"v\":1}",
                                 qos=2, packet_id=3, dup=True))
        _recv_packet(s)
        s.sendall(publish_packet("vehicles/sensor/data/car-1", b"{\"v\":1}",
                                 qos=2, packet_id=3, dup=True))
        _recv_packet(s)
        s.sendall(packet(PUBREL, 0x02, struct.pack(">H", 3)))
        h, _ = _recv_packet(s)
        assert h >> 4 == PUBCOMP
        s.close()
    assert bridge.forwarded() == 1
    total = sum(stream.end_offset("sensor-data", p) for p in range(2))
    assert total == 1


def test_qos2_dedup_state_survives_offline_expiry_cleanup():
    """Offline persistent sessions keep their unreleased QoS 2 ids (the
    reconnect dedup), and a clean_start reconnect wipes them."""
    broker = MqttBroker()
    sess = broker.connect("c1", lambda *a: None, clean_start=False)
    assert broker.qos2_begin(sess, 5) is True
    assert broker.qos2_begin(sess, 5) is False
    broker.disconnect("c1", sess)
    # persistent reconnect: id 5 still a duplicate
    sess2 = broker.connect("c1", lambda *a: None, clean_start=False)
    assert broker.qos2_begin(sess2, 5) is False
    broker.qos2_release(sess2, 5)
    assert broker.qos2_begin(sess2, 5) is True
    broker.disconnect("c1", sess2)
    # clean start wipes the handshake state
    sess3 = broker.connect("c1", lambda *a: None, clean_start=True)
    assert broker.qos2_begin(sess3, 5) is True
