"""Zero-copy produce plane (ISSUE 12): RAW_PRODUCE wire extension,
native write-path framing parity, byte-identical segments, whole-batch
corruption rejection, the fallback ladder, replica raw mirroring, the
fused KSQL produce leg, and the allocation contract."""

import gc
import json
import os
import tracemalloc

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.ops import framing
from iotml.ops.avro import AvroCodec
from iotml.store import segment as seg
from iotml.stream import native as native_mod
from iotml.stream.broker import Broker, CorruptMessageError
from iotml.stream.kafka_wire import (IDEMPOTENT_APIS, RAW_PRODUCE,
                                     KafkaWireBroker, KafkaWireServer)
from iotml.stream.producer import RawBatchProducer

NATIVE = native_mod.available()
needs_native = pytest.mark.skipif(not NATIVE,
                                  reason="C++ engine not built")

CODEC = AvroCodec(KSQL_CAR_SCHEMA)


def _entries(n=40, tombstones=()):
    return [(b"car-%d" % (i % 7),
             None if i in tombstones else b"payload-%d" % i,
             1000 + i)
            for i in range(n)]


def _log_bytes(store_dir, topic, partition):
    root = os.path.join(store_dir, "segments", topic, str(partition))
    out = b""
    for name in sorted(os.listdir(root)):
        if name.endswith(".log"):
            with open(os.path.join(root, name), "rb") as fh:
                out += fh.read()
    return out


# ------------------------------------------------ native == python oracle
@needs_native
def test_frame_entries_native_matches_python_oracle():
    """Opaque-value framing: native iotml_frames_encode_values output is
    bit-exact with the python store codec — null keys, tombstones,
    empty values, all of it."""
    entries = _entries(24, tombstones=(3, 17))
    entries[5] = (None, b"", 0)          # null key + empty value
    entries[9] = (b"", b"x" * 300, 5)    # empty (non-null) key
    native = framing.frame_entries(entries, base_offset=77)
    oracle = framing.encode_frame_batch(
        (77 + i, e[0], e[1], e[2], None) for i, e in enumerate(entries))
    assert native == oracle


@needs_native
def test_encode_frames_columnar_matches_python_oracle():
    """Fused columnar framing (Avro encode + Confluent header + store
    frame in one native call) is bit-exact with the python codec path,
    including NaN floats, null unions and message keys."""
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    rng = np.random.default_rng(7)
    n = 16
    numeric = rng.normal(size=(n, nc.n_numeric)).astype(np.float64)
    numeric[3, 2] = np.nan
    labels = np.array([["true" if i % 3 else "false"] for i in range(n)],
                      "S16")
    nulls = np.zeros((n, nc.n_fields), np.uint8)
    nulls[4, 0] = 1  # null union on a nullable field
    ts = np.arange(n, dtype=np.int64) + 500
    keys = [b"vehicles/sensor/data/car-%05d" % i for i in range(n)]
    blob = nc.encode_frames(numeric, labels, ts, keys=keys,
                            nulls=nulls, schema_id=1, base_offset=9)
    values = nc.encode_batch(numeric, labels, schema_id=1, nulls=nulls)
    oracle = framing.encode_frame_batch(
        (9 + i, keys[i], values[i], int(ts[i]), None) for i in range(n))
    assert blob == oracle
    # the S-dtype key array form (the zero-object fast path) agrees
    blob2 = nc.encode_frames(numeric, labels, ts,
                             keys=np.asarray(keys, "S64"),
                             nulls=nulls, schema_id=1, base_offset=9)
    assert blob2 == blob


def test_restamp_oracle_and_rejection_without_native(monkeypatch):
    """The pure-python restamp/validate oracles match the native path's
    semantics (the no-toolchain fallback contract)."""
    monkeypatch.setattr(framing, "_native_lib", lambda: None)
    entries = _entries(12, tombstones=(2,))
    frames = framing.frame_entries(entries)
    stamped, count, max_ts = framing.restamp_frame_batch(frames, 40)
    assert count == 12 and max_ts == 1011
    assert stamped == framing.encode_frame_batch(
        (40 + i, e[0], e[1], e[2], None)
        for i, e in enumerate(entries))
    bad = bytearray(frames)
    bad[len(bad) // 2] ^= 0xFF
    with pytest.raises(framing.CorruptFrameError):
        framing.restamp_frame_batch(bytes(bad), 0)
    v = framing.validate_frame_batch(stamped, start_offset=45)
    assert (v["count"], v["first"], v["last"]) == (7, 45, 51)
    assert stamped[v["start_pos"]:v["end_pos"]]


@needs_native
def test_restamp_native_matches_oracle(monkeypatch):
    frames = framing.frame_entries(_entries(9, tombstones=(1,)))
    native = framing.restamp_frame_batch(frames, 123)
    monkeypatch.setattr(framing, "_native_lib", lambda: None)
    oracle = framing.restamp_frame_batch(frames, 123)
    assert native == oracle


# ------------------------------------------------- segment byte parity
def test_raw_produce_segments_byte_identical_to_classic(tmp_path,
                                                        monkeypatch):
    """A topic ingested via RAW_PRODUCE is segment-byte-identical to the
    same records via classic produce — compaction/recovery/replica
    semantics untouched by construction."""
    entries = _entries(60, tombstones=(10, 44))
    frames = framing.frame_entries(entries)
    raw = Broker(store_dir=str(tmp_path / "raw"))
    raw.create_topic("t", partitions=1)
    raw.produce_raw("t", 0, frames)
    raw.flush()
    monkeypatch.setenv("IOTML_RAW_PRODUCE", "off")
    classic = Broker(store_dir=str(tmp_path / "classic"))
    classic.create_topic("t", partitions=1)
    for key, value, ts in entries:
        classic.produce("t", value, key=key, partition=0,
                        timestamp_ms=ts)
    classic.flush()
    assert _log_bytes(str(tmp_path / "raw"), "t", 0) == \
        _log_bytes(str(tmp_path / "classic"), "t", 0)
    # and both serve identical records (tombstones as value None)
    a = raw.fetch("t", 0, 0, 100)
    b = classic.fetch("t", 0, 0, 100)
    assert a == b
    assert a[10].value is None
    raw.close()
    classic.close()


def test_fused_produce_many_byte_identical(tmp_path, monkeypatch):
    """The durable broker's internal framing fusion (produce_many →
    one native frame batch per partition) produces byte-identical
    segments to the per-record python encoder."""
    entries = _entries(80)
    fused = Broker(store_dir=str(tmp_path / "fused"))
    fused.create_topic("t", partitions=3)
    fused.produce_many("t", entries)
    fused.flush()
    monkeypatch.setenv("IOTML_RAW_PRODUCE", "off")
    classic = Broker(store_dir=str(tmp_path / "classic"))
    classic.create_topic("t", partitions=3)
    classic.produce_many("t", entries)
    classic.flush()
    for p in range(3):
        assert _log_bytes(str(tmp_path / "fused"), "t", p) == \
            _log_bytes(str(tmp_path / "classic"), "t", p)
    fused.close()
    classic.close()


# ------------------------------------------- corruption: whole-batch NAK
def test_corrupt_batch_rejected_whole_before_any_byte_lands(tmp_path):
    broker = Broker(store_dir=str(tmp_path / "store"))
    broker.create_topic("t", partitions=1)
    frames = framing.frame_entries(_entries(30))
    broker.produce_raw("t", 0, frames)
    end = broker.end_offset("t", 0)
    broker.flush()
    size = os.path.getsize(
        os.path.join(str(tmp_path / "store"), "segments", "t", "0",
                     "00000000000000000000.log"))
    for cut in (3, len(frames) // 2, len(frames) - 2):
        bad = bytearray(frames)
        bad[cut] ^= 0xFF
        with pytest.raises(CorruptMessageError):
            broker.produce_raw("t", 0, bytes(bad))
        assert broker.end_offset("t", 0) == end
    # a torn tail (truncated batch) is rejected whole too
    with pytest.raises(CorruptMessageError):
        broker.produce_raw("t", 0, frames[:-7])
    broker.flush()
    assert os.path.getsize(
        os.path.join(str(tmp_path / "store"), "segments", "t", "0",
                     "00000000000000000000.log")) == size
    broker.close()


def test_chaos_corrupt_faultpoint_invariants(tmp_path):
    """Seeded chaos at broker.produce_raw: the corrupted batch is
    rejected whole (typed CORRUPT_MESSAGE over the wire), acked counts
    stay exact, and replay is byte-identical to an unfaulted control
    run after the producer redelivers."""
    from iotml.chaos import faults as chaos
    from iotml.chaos.scenarios import FaultEvent

    frames = [framing.frame_entries(_entries(20), base_offset=0)
              for _ in range(5)]

    def run(store, with_chaos):
        broker = Broker(store_dir=store)
        broker.create_topic("t", partitions=1)
        server = KafkaWireServer(broker)
        acked = 0
        rejected = 0
        with server:
            client = KafkaWireBroker(f"127.0.0.1:{server.port}")
            if with_chaos:
                chaos.arm(chaos.ChaosEngine([
                    FaultEvent(at=3, point="broker.produce_raw",
                               action="corrupt")]))
            try:
                for blob in frames:
                    for _attempt in range(2):
                        try:
                            client.produce_raw("t", 0, blob)
                            acked += 20
                            break
                        except CorruptMessageError:
                            rejected += 1  # redeliver: nothing landed
            finally:
                chaos.disarm()
                client.close()
        replay = broker.fetch("t", 0, 0, 1000)
        broker.flush()
        blob = _log_bytes(store, "t", 0)
        broker.close()
        return acked, rejected, replay, blob

    acked_c, rej_c, replay_c, bytes_c = run(str(tmp_path / "ctl"), False)
    acked_f, rej_f, replay_f, bytes_f = run(str(tmp_path / "flt"), True)
    assert (acked_c, rej_c) == (100, 0)
    assert (acked_f, rej_f) == (100, 1)  # injected, rejected, redelivered
    assert replay_f == replay_c          # acked counts + replay identical
    assert bytes_f == bytes_c            # byte-identical after rejection


# --------------------------------------------------- the fallback ladder
def test_raw_produce_less_server_pins_clients_back(monkeypatch):
    """A server without the RAW_PRODUCE extension answers
    UNSUPPORTED_VERSION; the client raises NotImplementedError and a
    RawBatchProducer (auto) pins back to classic PRODUCE — the stream
    content is identical either way."""
    from iotml.stream import kafka_wire as kw

    broker = Broker()
    broker.create_topic("t", partitions=1)
    supported = dict(kw._SUPPORTED)
    supported.pop(RAW_PRODUCE)
    monkeypatch.setattr(kw, "_SUPPORTED", supported)
    entries = _entries(15)
    frames = framing.frame_entries(entries)
    with KafkaWireServer(broker) as server:
        client = KafkaWireBroker(f"127.0.0.1:{server.port}")
        with pytest.raises(NotImplementedError):
            client.produce_raw("t", 0, frames)
        producer = RawBatchProducer(client, "t", mode="auto")
        base = producer.produce_frames(0, frames, len(entries),
                                       entries=entries)
        assert base == 0 and producer.engaged is False
        # pinned: the second batch goes classic without re-probing
        producer.produce_frames(0, frames, len(entries), entries=entries)
        assert producer.classic_records == 30
        with pytest.raises(NotImplementedError):
            RawBatchProducer(client, "t", mode="on").produce_frames(
                0, frames, len(entries))
        client.close()
    assert [(m.key, m.value, m.timestamp_ms)
            for m in broker.fetch("t", 0, 0, 100)] == entries * 2


def test_raw_produce_deliberately_not_idempotent():
    """RAW_PRODUCE mutates the log: a blind retry double-appends, so it
    is handled in the idempotency table deliberately — absent, like
    PRODUCE (caller-owns-redelivery)."""
    from iotml.analysis import lint as lint_mod
    from iotml.stream import kafka_wire as kw

    assert RAW_PRODUCE in kw._SUPPORTED
    assert RAW_PRODUCE not in IDEMPOTENT_APIS
    assert "RAW_PRODUCE" not in lint_mod.IDEMPOTENT_API_NAMES


def test_knobs_validated_and_not_config(monkeypatch):
    """IOTML_RAW_PRODUCE / IOTML_PRODUCE_BATCH_BYTES are process knobs
    (config non_config — they must not be rejected as unknown config
    sections), validated loudly."""
    from iotml.config import load_config
    from iotml.data.pipeline import (produce_batch_bytes,
                                     raw_produce_mode, set_knobs)

    monkeypatch.setenv("IOTML_RAW_PRODUCE", "auto")
    monkeypatch.setenv("IOTML_PRODUCE_BATCH_BYTES", "65536")
    cfg, _ = load_config([])  # no ValueError: both are non_config
    assert raw_produce_mode() == "auto"
    assert produce_batch_bytes() == 65536
    monkeypatch.setenv("IOTML_RAW_PRODUCE", "sometimes")
    with pytest.raises(ValueError):
        raw_produce_mode()
    monkeypatch.setenv("IOTML_PRODUCE_BATCH_BYTES", "12")
    with pytest.raises(ValueError):
        produce_batch_bytes()
    with pytest.raises(ValueError):
        set_knobs(raw_produce="maybe")
    with pytest.raises(ValueError):
        set_knobs(produce_batch_bytes=16)
    # a failed set_knobs must not have published anything
    assert os.environ["IOTML_PRODUCE_BATCH_BYTES"] == "12"
    set_knobs(raw_produce="off", produce_batch_bytes=8192)
    assert raw_produce_mode() == "off"
    assert produce_batch_bytes() == 8192


# ------------------------------------------------- replica raw mirroring
def test_replica_mirrors_raw_batches_byte_identical(tmp_path):
    from iotml.stream.replica import FollowerReplica

    leader_dir = str(tmp_path / "leader")
    follower_dir = str(tmp_path / "follower")
    leader = Broker(store_dir=leader_dir)
    leader.create_topic("t", partitions=2)
    for i in range(300):
        leader.produce("t", b"v%d" % i, key=b"k%d" % (i % 5),
                       timestamp_ms=i)
    with KafkaWireServer(leader) as server:
        rep = FollowerReplica(f"127.0.0.1:{server.port}", topics=["t"],
                              groups=("g",), store_dir=follower_dir)
        copied = rep.sync_once()
        assert copied == 300
        assert rep.raw_mirrored == 300  # the zero-copy leg carried it
        leader.flush()
        rep.local.flush()
        for p in range(2):
            assert _log_bytes(leader_dir, "t", p) == \
                _log_bytes(follower_dir, "t", p)
        # realignment semantics unchanged: trim the leader past the
        # follower's cursor and the follower resets, not shifts
        leader.reset_partition("t", 0, 500)
        leader.produce("t", b"post-trim", partition=0, timestamp_ms=999)
        rep.sync_once()
        assert rep.local.begin_offset("t", 0) == 500
        assert rep.local.fetch("t", 0, 500, 5)[0].value == b"post-trim"
        assert any("realigned" in e for e in rep.sync_errors)
        rep.local.close()
        try:
            rep._leader.close()
        except OSError:
            pass
    leader.close()


def test_replica_partition_filter_on_raw_leg(tmp_path):
    from iotml.stream.replica import FollowerReplica

    leader = Broker(store_dir=str(tmp_path / "leader"))
    leader.create_topic("t", partitions=2)
    for i in range(100):
        leader.produce("t", b"v%d" % i, partition=i % 2, timestamp_ms=i)
    with KafkaWireServer(leader) as server:
        rep = FollowerReplica(f"127.0.0.1:{server.port}", topics=["t"],
                              store_dir=str(tmp_path / "follower"),
                              partition_filter=lambda t, p: p == 1)
        assert rep.sync_once() == 50
        assert rep.local.end_offset("t", 1) == 50
        assert rep.local.end_offset("t", 0) == 0  # unowned: untouched
        rep.local.close()
        try:
            rep._leader.close()
        except OSError:
            pass
    leader.close()


def test_replica_oversized_record_falls_back_to_classic(tmp_path,
                                                        monkeypatch):
    """A record larger than the raw-batch byte cap tears every raw
    fetch at the cursor: the mirror must hand that batch to the classic
    per-record leg instead of reading 'caught up' and parking forever
    (regression)."""
    from iotml.stream.replica import FollowerReplica

    monkeypatch.setenv("IOTML_RAW_BATCH_BYTES", "4096")
    leader = Broker(store_dir=str(tmp_path / "leader"))
    leader.create_topic("t", partitions=1)
    leader.produce("t", b"small", partition=0, timestamp_ms=1)
    leader.produce("t", b"x" * 16384, partition=0, timestamp_ms=2)
    leader.produce("t", b"tail", partition=0, timestamp_ms=3)
    with KafkaWireServer(leader) as server:
        rep = FollowerReplica(f"127.0.0.1:{server.port}", topics=["t"],
                              store_dir=str(tmp_path / "follower"))
        assert rep.sync_once() == 3
        msgs = rep.local.fetch("t", 0, 0, 10)
        assert [m.value for m in msgs] == [b"small", b"x" * 16384,
                                           b"tail"]
        rep.local.close()
        try:
            rep._leader.close()
        except OSError:
            pass
    leader.close()


def test_replica_pins_classic_when_leader_lacks_raw(tmp_path,
                                                    monkeypatch):
    from iotml.stream import kafka_wire as kw
    from iotml.stream.replica import FollowerReplica

    leader = Broker(store_dir=str(tmp_path / "leader"))
    leader.create_topic("t", partitions=1)
    for i in range(40):
        leader.produce("t", b"v%d" % i, timestamp_ms=i)
    supported = dict(kw._SUPPORTED)
    supported.pop(kw.RAW_FETCH)
    monkeypatch.setattr(kw, "_SUPPORTED", supported)
    with KafkaWireServer(leader) as server:
        rep = FollowerReplica(f"127.0.0.1:{server.port}", topics=["t"],
                              store_dir=str(tmp_path / "follower"))
        assert rep.sync_once() == 40
        assert rep.raw_mirrored == 0
        assert rep._raw_mirror is False  # pinned back permanently
        assert rep.local.end_offset("t", 0) == 40
        rep.local.close()
        try:
            rep._leader.close()
        except OSError:
            pass
    leader.close()


# ------------------------------------------------- cluster-routed appends
def test_cluster_client_routes_raw_batches_to_owning_shards():
    from iotml.cluster import ClusterController

    ctl = ClusterController(brokers=3).start()
    try:
        ctl.create_topic("t", partitions=6)
        cli = ctl.client()
        frames = framing.frame_entries(_entries(10))
        for p in range(6):
            base = cli.produce_raw("t", p, frames)
            assert base == 0
        for p in range(6):
            assert cli.end_offset("t", p) == 10
        # the shard actually holding the partition served the append
        for i, b in enumerate(ctl.brokers):  # lint-not-applicable: tests
            for p in range(6):
                if b.owns("t", p):
                    assert b.end_offset("t", p) == 10
        cli.close()
    finally:
        ctl.stop()


# ------------------------------------------------ fused KSQL produce leg
@needs_native
def test_pump_raw_leg_output_identical_to_classic(tmp_path, monkeypatch):
    """The AVRO CSAS's fused JSON→frames leg (RAW_PRODUCE) emits the
    same topic content as the classic python path — keys, bytes,
    timestamps, partitioning."""
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream import SchemaRegistry
    from iotml.stream.producer import raw_produce_records
    from iotml.streamproc import SqlEngine
    from iotml.streamproc.sql import install_reference_pipeline

    def run(mode, store):
        monkeypatch.setenv("IOTML_RAW_PRODUCE", mode)
        broker = Broker(store_dir=store)
        broker.create_topic("sensor-data", partitions=4)
        engine = SqlEngine(broker, registry=SchemaRegistry())
        install_reference_pipeline(engine)
        gen = FleetGenerator(FleetScenario(num_cars=16,
                                           failure_rate=0.05, seed=5))
        for tick in range(8):
            cols = gen.step_columns()
            broker.produce_many("sensor-data", [
                (b"vehicles/sensor/data/car-%05d" % i,
                 json.dumps(gen.row_record(cols, i,
                                           KSQL_CAR_SCHEMA)).encode(),
                 1000 + tick)
                for i in range(16)])
        engine.pump()
        spec = broker.topic("SENSOR_DATA_S_AVRO")
        out = [[(m.offset, m.key, m.value, m.timestamp_ms)
                for m in broker.fetch("SENSOR_DATA_S_AVRO", p, 0, 10000)]
               for p in range(spec.partitions)]
        broker.close()
        return out

    before = raw_produce_records.value()
    got_raw = run("auto", str(tmp_path / "raw"))
    assert raw_produce_records.value() > before  # the raw leg carried it
    got_classic = run("off", str(tmp_path / "classic"))
    assert got_raw == got_classic


# --------------------------------------- zero per-record allocation path
@needs_native
def test_zero_per_record_python_objects_on_fused_produce_path(tmp_path):
    """PR 10's consume assertion, mirrored for produce: shipping 16x
    more records through columnar-frames → RAW_PRODUCE must NOT
    allocate ~16x more Python objects — per-batch cost is O(1)."""
    broker = Broker(store_dir=str(tmp_path / "store"))
    broker.create_topic("t", partitions=1)
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    rng = np.random.default_rng(3)
    numeric = rng.normal(size=(2048, nc.n_numeric)).astype(np.float64)
    labels = np.full((2048, nc.n_strings), b"false", "S16")
    ts = np.arange(2048, dtype=np.int64)
    keys = np.asarray([b"car-%04d" % (i % 50) for i in range(2048)],
                      "S64")

    def count_allocs(rows):
        # warm everything (codec scratch, broker topic path)
        blob = nc.encode_frames(numeric[:8], labels[:8], ts[:8],
                                keys=keys[:8], schema_id=1)
        broker.produce_raw("t", 0, blob)
        gc.collect()
        tracemalloc.start()
        blob = nc.encode_frames(numeric[:rows], labels[:rows], ts[:rows],
                                keys=keys[:rows], schema_id=1)
        broker.produce_raw("t", 0, blob)
        snap = tracemalloc.take_snapshot()
        tracemalloc.stop()
        return sum(s.count for s in snap.statistics("filename"))

    small = count_allocs(128)
    big = count_allocs(2048)
    assert big < small * 2 + 64, (small, big)
    broker.close()


# -------------------------------------------------- engine-owned topics
def test_raw_produce_respects_topic_ownership():
    broker = Broker()
    broker.create_topic("OWNED_TOPIC", partitions=1)
    token = broker.restrict_topic("OWNED_")
    frames = framing.frame_entries(_entries(5))
    with pytest.raises(PermissionError):
        broker.produce_raw("OWNED_TOPIC", 0, frames)
    with broker.producer_grant(token):
        assert broker.produce_raw("OWNED_TOPIC", 0, frames) == 0


def test_fetch_raw_jumps_compaction_emptied_head_segment(tmp_path):
    """A compaction pass that empties the head segment (zero bytes,
    base preserved) must not read as log end on the raw path: fetch_raw
    jumps to the successor exactly like read_from's hole jump — the
    replica's raw mirror leg parks forever otherwise (regression)."""
    from iotml.store.log import StorePolicy

    broker = Broker(store_dir=str(tmp_path / "store"),
                    store_policy=StorePolicy(segment_bytes=256))
    broker.create_topic("C", cleanup_policy="compact")
    for rnd in range(8):
        for k in range(4):
            broker.produce("C", b"v%d" % rnd, key=b"k%d" % k,
                           partition=0, timestamp_ms=1000 + rnd)
    broker.store.log_for("C", 0).roll()
    broker.run_compaction(force=True)
    survivors = broker.fetch("C", 0, 0, 1000)
    raw = broker.fetch_raw("C", 0, 0)
    assert raw is not None
    v = framing.validate_frame_batch(raw.data, start_offset=0)
    assert v["first"] == survivors[0].offset
    assert v["count"] >= 1
    broker.close()


def test_wire_raw_produce_tombstones_roundtrip():
    """Tombstones framed into a raw batch land as value-None records
    over the wire (the compaction delete-marker contract)."""
    broker = Broker()
    broker.create_topic("t", partitions=1)
    frames = framing.frame_entries(_entries(6, tombstones=(2, 5)))
    with KafkaWireServer(broker) as server:
        client = KafkaWireBroker(f"127.0.0.1:{server.port}")
        client.produce_raw("t", 0, frames)
        client.close()
    msgs = broker.fetch("t", 0, 0, 10)
    assert msgs[2].value is None and msgs[5].value is None
    assert msgs[0].value == b"payload-0"
