"""Schema registry: Confluent semantics (ids, versions, idempotence) and
avsc round-trip against both reference schema variants."""

import json

import pytest

from iotml.core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
from iotml.ops.avro import AvroCodec
from iotml.ops.framing import frame, unframe
from iotml.stream.registry import (SchemaRegistry, fingerprint, parse_avsc,
                                   subject_for_topic)


def test_register_and_lookup():
    reg = SchemaRegistry()
    sid = reg.register("sensor-data-value", CAR_SCHEMA.avro_json())
    assert sid == 1
    rs = reg.by_id(sid)
    assert rs.subject == "sensor-data-value" and rs.version == 1
    assert reg.latest("sensor-data-value").schema_id == sid


def test_idempotent_registration_same_id():
    reg = SchemaRegistry()
    a = reg.register("s-value", CAR_SCHEMA.avro_json())
    b = reg.register("s-value", CAR_SCHEMA.avro_json())
    assert a == b
    assert reg.latest("s-value").version == 1  # no duplicate version


def test_schema_evolution_versions():
    reg = SchemaRegistry()
    v1 = reg.register("s-value", CAR_SCHEMA.avro_json())
    v2 = reg.register("s-value", KSQL_CAR_SCHEMA.avro_json())
    assert v2 != v1
    assert reg.latest("s-value").schema_id == v2
    assert reg.version("s-value", 1).schema_id == v1
    # the same schema under another subject keeps its global id
    other = reg.register("other-value", CAR_SCHEMA.avro_json())
    assert other == v1
    assert reg.latest("other-value").version == 1


def test_check_and_errors():
    reg = SchemaRegistry()
    assert reg.check("s-value", CAR_SCHEMA.avro_json()) is None
    sid = reg.register("s-value", CAR_SCHEMA.avro_json())
    assert reg.check("s-value", CAR_SCHEMA.avro_json()) == sid
    with pytest.raises(KeyError):
        reg.by_id(99)
    with pytest.raises(KeyError):
        reg.latest("nope")
    with pytest.raises(ValueError):
        reg.register("s-value", "{not json")


def test_parse_avsc_roundtrip_both_variants():
    for schema in (CAR_SCHEMA, KSQL_CAR_SCHEMA):
        parsed = parse_avsc(schema.avro_json())
        assert parsed.field_names == schema.field_names
        assert [f.avro_type for f in parsed.fields] == \
            [f.avro_type for f in schema.fields]
        assert [f.nullable for f in parsed.fields] == \
            [f.nullable for f in schema.fields]
        assert parsed.label_field == schema.label_field


_REFERENCE_AVSC = ("/root/reference/python-scripts/AUTOENCODER-TensorFlow-IO-"
                   "Kafka/cardata-v1.avsc")


def test_parse_reference_avsc_file():
    """The KSQL-derived schema the reference ML apps actually load."""
    import os

    if not os.path.exists(_REFERENCE_AVSC):
        # the conftest guard checks only the checkout root; a partial
        # mount (root present, file absent) must skip, not fail
        pytest.skip("reference avsc not mounted")
    avsc = open(_REFERENCE_AVSC).read()
    schema = parse_avsc(avsc)
    assert len(schema.fields) == 19
    assert schema.label_field == "FAILURE_OCCURRED"
    assert all(f.nullable for f in schema.fields)
    # and the codec round-trips a record under it
    codec = AvroCodec(schema)
    rec = {f.name: (1.5 if f.avro_type == "double" else
                    3 if f.avro_type == "int" else "false")
           for f in schema.fields}
    assert codec.decode(codec.encode(rec)) == rec


def test_registry_framing_integration():
    """Wire path: register → frame with the real id → unframe → resolve."""
    reg = SchemaRegistry()
    sid = reg.register(subject_for_topic("SENSOR_DATA_S_AVRO"),
                       KSQL_CAR_SCHEMA.avro_json())
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = {f.name: (0.5 if f.avro_type == "double" else
                    1 if f.avro_type == "int" else "false")
           for f in KSQL_CAR_SCHEMA.fields}
    msg = frame(codec.encode(rec), schema_id=sid)
    got_id, payload = unframe(msg)
    schema = reg.by_id(got_id).record_schema
    assert AvroCodec(schema).decode(payload) == rec


def test_fingerprint_whitespace_invariant():
    a = CAR_SCHEMA.avro_json()
    b = json.dumps(json.loads(a))  # different formatting
    assert a != b and fingerprint(a) == fingerprint(b)
