"""Follower replication + leader failover (stream/replica.py).

The reference's stream plane is replicated managed infrastructure (RF-3
topics on 3 brokers, 01_installConfluentPlatform.sh:180-183); the
rebuild's minimum equivalent is a pull follower serving the same wire
protocol at identical offsets, with failover living in the client's
bootstrap list.
"""

import time

import pytest

from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer
from iotml.stream.replica import FollowerReplica


def _leader_with_data(n_ticks=20, partitions=2, retention=None):
    broker = Broker()
    broker.create_topic("T", partitions=partitions,
                        retention_messages=retention)
    gen = FleetGenerator(FleetScenario(num_cars=30, seed=7))
    gen.publish(broker, "T", n_ticks=n_ticks, partitions=partitions)
    srv = KafkaWireServer(broker).start()
    return broker, srv, gen


def _all_messages(broker_like, topic, partitions):
    out = {}
    for p in range(partitions):
        msgs, off = [], 0
        while True:
            chunk = broker_like.fetch(topic, p, off, 1000)
            if not chunk:
                break
            msgs.extend((m.offset, m.key, m.value, m.timestamp_ms)
                        for m in chunk)
            off = chunk[-1][2] + 1 if hasattr(chunk[-1], "offset") else 0
            off = msgs[-1][0] + 1
        out[p] = msgs
    return out


def test_follower_mirrors_messages_offsets_and_commits():
    broker, srv, gen = _leader_with_data()
    try:
        leader_client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        leader_client.commit("g1", "T", 0, 123)
        leader_client.commit("g1", "T", 1, 45)
        with FollowerReplica(f"127.0.0.1:{srv.port}", topics=["T"],
                             groups=("g1",)) as rep:
            assert rep.caught_up(timeout_s=15)
            # one more round so the group table sync has run at least once
            rep.sync_once()
            want = _all_messages(broker, "T", 2)
            got = _all_messages(rep.local, "T", 2)
            assert want == got and all(want.values())
            assert rep.local.committed("g1", "T", 0) == 123
            assert rep.local.committed("g1", "T", 1) == 45
            assert rep.lag() == {"T": 0}
        leader_client.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_late_start_follower_aligns_trimmed_base_offset():
    """A follower starting after retention trimmed the leader's log head
    must mirror at IDENTICAL absolute offsets (consumer cursors survive
    failover unchanged), starting from the earliest retained offset."""
    broker, srv, gen = _leader_with_data(n_ticks=40, partitions=1,
                                         retention=300)
    try:
        assert broker.begin_offset("T", 0) > 0  # head actually trimmed
        with FollowerReplica(f"127.0.0.1:{srv.port}", topics=["T"]) as rep:
            assert rep.caught_up(timeout_s=15)
            assert rep.local.begin_offset("T", 0) == \
                broker.begin_offset("T", 0)
            assert rep.local.end_offset("T", 0) == broker.end_offset("T", 0)
            off = broker.begin_offset("T", 0) + 5
            assert [m.value for m in rep.local.fetch("T", 0, off, 10)] == \
                [m.value for m in broker.fetch("T", 0, off, 10)]
    finally:
        srv.shutdown()
        srv.server_close()


def test_consumer_survives_leader_death_mid_drain():
    """The failover contract end to end: a consumer bootstrapped with
    "leader,follower" drains half the stream from the leader, commits,
    the leader DIES (accept loop + every live connection), and the same
    consumer object keeps draining from the follower at the same
    offsets — every record delivered exactly once across the failover,
    and committed offsets survive for a crash-restart."""
    broker, srv, gen = _leader_with_data(n_ticks=20, partitions=2)
    total = sum(len(v) for v in _all_messages(broker, "T", 2).values())
    rep = FollowerReplica(f"127.0.0.1:{srv.port}", topics=["T"],
                          groups=("g2",)).start()
    try:
        assert rep.caught_up(timeout_s=15)
        client = KafkaWireBroker(
            f"127.0.0.1:{srv.port},127.0.0.1:{rep.port}")
        consumer = StreamConsumer(client, [f"T:{p}:0" for p in range(2)],
                                  group="g2")
        seen = []
        deadline = time.monotonic() + 20
        while len(seen) < total // 2 and time.monotonic() < deadline:
            for m in consumer.poll(200):
                seen.append((m.partition, m.offset, m.value))
        assert len(seen) >= total // 2
        consumer.commit()
        # Supervised barrier (replaces BOTH earlier deflake attempts):
        # pause() parks the background replication loop BETWEEN rounds,
        # so the explicit sync_once() below races nothing and the kill
        # cannot land mid-round.  The pre-barrier versions — driving
        # sync_once() concurrently with the loop, then poll-until-
        # deadline on the mirrored-commit condition — both left a
        # window where the loop's own round interleaved with the kill
        # and occasionally flaked; the barrier removes the window
        # instead of timing around it.
        assert rep.pause()
        rep.sync_once()  # deterministic mirror: nothing else is syncing
        want = {p: off for _, p, off in consumer.positions()}
        assert all(rep.local.committed("g2", "T", p) == want[p]
                   for p in range(2))
        # the leader dies abruptly, with replication quiescent
        srv.kill()
        rep.resume()
        deadline = time.monotonic() + 20
        while len(seen) < total and time.monotonic() < deadline:
            try:
                batch = consumer.poll(200)
            except ConnectionError:
                # kill() can race an in-flight fetch AND its one
                # post-reconnect retry (half-closed leader socket):
                # transient during failover — re-poll until the deadline
                time.sleep(0.05)
                continue
            for m in batch:
                seen.append((m.partition, m.offset, m.value))
        assert len(seen) == total
        # exactly once across the failover: offsets contiguous per
        # partition, no gap, no duplicate
        for p in range(2):
            offs = sorted(o for pp, o, _ in seen if pp == p)
            assert offs == list(range(len(offs)))
        # a crash-restart resumes from the replicated committed offsets
        # against the follower alone — EXACTLY the offsets committed
        # before the kill (the barrier made the mirror deterministic,
        # so this is equality, not the old tautological >= 0 check)
        c2 = StreamConsumer.from_committed(
            KafkaWireBroker(f"127.0.0.1:{rep.port}"), "T", range(2),
            group="g2")
        positions = {p: off for _, p, off in c2.positions()}
        assert positions == want
    finally:
        rep.stop()
        try:
            srv.server_close()
        except OSError:
            pass


class _RejectingSaslServer:
    """Minimal wire server that ACCEPTS the SASL handshake but then
    explicitly REJECTS the PLAIN token (non-empty auth response) — the
    behavior real brokers show for bad credentials, which our fixture
    server does not (it drops the connection instead).  Counts accepted
    connections so a test can pin the no-retry contract."""

    def __init__(self):
        import socket as _socket
        import struct
        import threading

        self._struct = struct
        self.sock = _socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.connections = 0
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        struct = self._struct
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.connections += 1
            try:
                # handshake request frame → OK + ["PLAIN"]
                (size,) = struct.unpack(">i", conn.recv(4))
                frame = conn.recv(size)
                corr = struct.unpack(">i", frame[4:8])[0]
                body = (struct.pack(">i", corr) + struct.pack(">h", 0)
                        + struct.pack(">i", 1)
                        + struct.pack(">h", 5) + b"PLAIN")
                conn.sendall(struct.pack(">i", len(body)) + body)
                # raw token frame → explicit non-empty REJECTION
                (size,) = struct.unpack(">i", conn.recv(4))
                conn.recv(size)
                conn.sendall(struct.pack(">i", 4) + b"nope")
            except OSError:
                pass
            finally:
                conn.close()

    def close(self):
        self.sock.close()


def test_failover_rejects_bad_credentials_without_retry_spam():
    """An EXPLICIT SASL rejection raises SaslAuthError immediately and
    is NOT retried against the rest of the bootstrap list — wrong
    credentials are wrong everywhere, and retrying them fleet-wide is
    auth-failure spam (the pre-fix client did exactly that, leaking one
    socket per server on the way)."""
    from iotml.stream.kafka_wire import SaslAuthError

    a, b = _RejectingSaslServer(), _RejectingSaslServer()
    try:
        with pytest.raises(SaslAuthError):
            KafkaWireBroker(f"127.0.0.1:{a.port},127.0.0.1:{b.port}",
                            sasl_username="svc", sasl_password="wrong")
        # the FIRST server rejected; the second must never see a try
        assert a.connections == 1 and b.connections == 0
    finally:
        a.close()
        b.close()


def test_bad_credentials_against_fixture_server_fail_closed():
    """The fixture server drops bad-token connections (pre-KIP-152):
    construction must still fail (as connectivity), and correct
    credentials must work."""
    broker = Broker()
    broker.create_topic("T")
    srv = KafkaWireServer(broker, credentials=("svc", "right")).start()
    try:
        with pytest.raises(ConnectionError):
            KafkaWireBroker(f"127.0.0.1:{srv.port}",
                            sasl_username="svc", sasl_password="wrong")
        good = KafkaWireBroker(f"127.0.0.1:{srv.port}",
                               sasl_username="svc", sasl_password="right")
        assert good.topics() == ["T"]
        good.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_failover_survives_mid_request_reconnect_with_sasl():
    """The failover path re-authenticates: a SASL-protected pair, leader
    dies, the client's next request reconnects to the follower (which is
    open — fixture semantics) or errors cleanly; with both servers
    credentialed the request succeeds after re-auth."""
    broker = Broker()
    broker.create_topic("T")
    broker.produce("T", b"x", key=b"k")
    srv_a = KafkaWireServer(broker, credentials=("svc", "pw")).start()
    srv_b = KafkaWireServer(broker, credentials=("svc", "pw")).start()
    try:
        client = KafkaWireBroker(
            f"127.0.0.1:{srv_a.port},127.0.0.1:{srv_b.port}",
            sasl_username="svc", sasl_password="pw")
        assert client.end_offset("T", 0) == 1
        srv_a.kill()
        # next request fails over to B and re-runs the SASL handshake
        assert client.end_offset("T", 0) == 1
        client.close()
    finally:
        for s in (srv_a, srv_b):
            try:
                s.shutdown()
                s.server_close()
            except OSError:
                pass


def test_commit_mirror_throttled_and_batched():
    """Idle sync rounds must not hammer the leader with offset fetches:
    the background cadence (sync_once(mirror_commits=None)) mirrors only
    after rounds that copied messages or once per commit_interval_s —
    and each mirror is ONE OffsetFetch per group, not one per
    partition.  Direct sync_once() keeps mirroring unconditionally
    (deterministic test semantics)."""
    broker, srv, _gen = _leader_with_data(n_ticks=4, partitions=2)
    try:
        leader_client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        leader_client.commit("g", "T", 0, 3)
        rep = FollowerReplica(f"127.0.0.1:{srv.port}", topics=["T"],
                              groups=("g",), commit_interval_s=3600.0)
        # first round copies messages -> mirrors commits despite cadence
        assert rep.sync_once(mirror_commits=None) > 0
        assert rep.local.committed("g", "T", 0) == 3
        # caught up + a fresh leader-side commit: a cadence round must
        # SKIP the mirror (nothing copied, interval not elapsed)...
        leader_client.commit("g", "T", 0, 4)
        corr_before = rep._leader._corr
        assert rep.sync_once(mirror_commits=None) == 0
        assert rep.local.committed("g", "T", 0) == 3
        # ...and the skipped round made zero OffsetFetch round-trips
        # (remaining requests are the topic/fetch probes only)
        reqs = rep._leader._corr - corr_before
        assert reqs <= 1 + 2  # metadata refresh + one fetch per partition
        # interval elapsed -> cadence round mirrors again, in ONE request
        rep._last_commit_sync = float("-inf")
        corr_before = rep._leader._corr
        assert rep.sync_once(mirror_commits=None) == 0
        assert rep.local.committed("g", "T", 0) == 4
        # explicit sync_once(): unconditional mirror
        leader_client.commit("g", "T", 1, 9)
        rep.sync_once()
        assert rep.local.committed("g", "T", 1) == 9
        rep._leader.close()
        leader_client.close()
    finally:
        srv.shutdown()
        srv.server_close()
