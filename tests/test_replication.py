"""Quorum ISR durability + elastic reassignment (iotml.replication).

The reference runs every topic at RF 3 (01_installConfluentPlatform.sh);
this suite pins the rebuild's Kafka-shape equivalent: leader-side ISR
tracking from replica-stamped fetches, acks=all at the quorum
high-water mark, the consumer read barrier (no reads of the
un-replicated tail), staleness eviction / re-admission, ISR-restricted
election, HWM persistence across remount, and online add/drain
reassignment on the cluster.
"""

import os
import threading
import time

import pytest

from iotml.replication import ReplicaSet, ReplicationState
from iotml.stream.broker import Broker
from iotml.stream.kafka_wire import (KafkaWireBroker, KafkaWireServer,
                                     NotEnoughReplicasError,
                                     ProduceTimedOutError)

T = "repl-topic"


def _leader_with_set(n_followers=2, min_isr=2, max_lag_s=0.3,
                     partitions=1, groups=(), hwm_file=None,
                     store_dir=None):
    leader = Broker(store_dir=store_dir)
    leader.create_topic(T, partitions=partitions)
    srv = KafkaWireServer(leader).start()
    rs = ReplicaSet(leader_broker=leader, leader_server=srv,
                    n_followers=n_followers, min_isr=min_isr,
                    max_lag_s=max_lag_s, topics=[T], groups=groups,
                    hwm_file=hwm_file)
    rs.start(sync="manual")
    return leader, srv, rs


def _teardown(srv, rs, *clients):
    for c in clients:
        try:
            c.close()
        except OSError:
            pass
    rs.stop()
    try:
        srv.shutdown()
        srv.server_close()
    except OSError:
        pass


def _form_isr(rs, partitions=1, width=None):
    want = width if width is not None else 1 + len(rs.followers)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rs.sync_once()
        if all(rs.state.isr_size(T, p) >= want
               for p in range(partitions)):
            return
    raise AssertionError(
        f"ISR never formed: {rs.state.isr_size(T, 0)} < {want}")


def _quorum_produce(client, rs, values, partition=0, timeout_s=10.0):
    """acks=all produce resolved against manually-stepped followers:
    the wait blocks a server handler thread, so the produce runs on a
    worker while the test thread steps replication."""
    result = {}

    def attempt():
        try:
            result["last"] = client.produce_many(
                T, [(None, v, 0) for v in values], partition=partition)
        except Exception as e:  # noqa: BLE001 - surfaced to the test
            result["err"] = e

    t = threading.Thread(target=attempt, daemon=True,
                         name="iotml-test-quorum-produce")
    t.start()
    deadline = time.monotonic() + timeout_s
    while t.is_alive() and time.monotonic() < deadline:
        rs.sync_once()
        time.sleep(0.002)
    t.join(1.0)
    if "err" in result:
        raise result["err"]
    assert "last" in result, "quorum produce never resolved"
    return result["last"]


# ----------------------------------------------------------- ISR unit
def test_isr_admission_requires_catch_up():
    broker = Broker()
    broker.create_topic(T)
    broker.produce_batch(T, [b"a", b"b", b"c"], partition=0)
    state = ReplicationState(broker, follower_ids=(1,), min_isr=2)
    # registered but never fetched: out of the ISR
    assert state.isr_size(T, 0) == 1
    # a mid-log fetch is progress, not membership
    state.observe_fetch(1, T, 0, 1)
    assert state.isr_size(T, 0) == 1
    # reaching the log end admits
    state.observe_fetch(1, T, 0, 3)
    assert state.isr_size(T, 0) == 2
    assert state.isr_follower_ids() == {1}


def test_quorum_hwm_is_min_over_isr_and_monotone():
    broker = Broker()
    broker.create_topic(T)
    state = ReplicationState(broker, follower_ids=(1, 2), min_isr=2,
                             max_lag_s=30.0)
    broker.produce_batch(T, [b"a", b"b"], partition=0)
    # anchor: attaching replication must not un-commit history — the
    # first touch anchors the hwm at the then-current end
    assert state.quorum_hwm(T, 0) == 2
    state.observe_fetch(1, T, 0, 2)
    state.observe_fetch(2, T, 0, 2)
    broker.produce_batch(T, [b"c", b"d"], partition=0)  # end=4
    # follower 1 reaches 3, follower 2 reaches 4: quorum = min = 3
    state.observe_fetch(1, T, 0, 3)
    state.observe_fetch(2, T, 0, 4)
    assert state.quorum_hwm(T, 0) == 3
    assert state.fetch_ceiling(T, 0) == 3
    # monotone: nothing can pull it back
    state.observe_fetch(1, T, 0, 4)
    assert state.quorum_hwm(T, 0) == 4


def test_staleness_eviction_and_readmission():
    broker = Broker()
    broker.create_topic(T)
    state = ReplicationState(broker, follower_ids=(1,), min_isr=1,
                             max_lag_s=0.1)
    broker.produce_batch(T, [b"a"], partition=0)
    state.observe_fetch(1, T, 0, 1)
    assert state.isr_size(T, 0) == 2
    # the follower freezes while the log grows: evicted after the window
    broker.produce_batch(T, [b"b"], partition=0)
    time.sleep(0.15)
    state.evict_stale()
    assert state.isr_size(T, 0) == 1
    # quorum advanced past the evicted laggard (leader-only ISR)
    assert state.quorum_hwm(T, 0) == 2
    # catch-up re-admits
    state.observe_fetch(1, T, 0, 2)
    assert state.isr_size(T, 0) == 2


def test_unregister_advances_quorum():
    broker = Broker()
    broker.create_topic(T)
    state = ReplicationState(broker, follower_ids=(1, 2), min_isr=1,
                             max_lag_s=30.0)
    state.observe_fetch(1, T, 0, 0)
    state.observe_fetch(2, T, 0, 0)
    broker.produce_batch(T, [b"a", b"b"], partition=0)
    state.observe_fetch(2, T, 0, 2)
    assert state.quorum_hwm(T, 0) == 0  # bounded by follower 1
    state.unregister_follower(1)
    assert state.quorum_hwm(T, 0) == 2
    assert state.follower_ids == (2,)


# ------------------------------------------------------ acks semantics
def test_acks_all_without_replication_is_leader_ack():
    """Kafka RF-1: ISR = {leader}, acks=all == acks=1 — the classic
    client default keeps working against every unreplicated broker."""
    broker = Broker()
    broker.create_topic(T)
    srv = KafkaWireServer(broker).start()
    try:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        assert client.produce_many(T, [(None, b"v", 0)],
                                   partition=0) == 0  # default acks=-1
        client.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_acks_all_rejected_below_min_isr_nothing_appended():
    leader, srv, rs = _leader_with_set()
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        with pytest.raises(NotEnoughReplicasError):
            client.produce_many(T, [(None, b"v", 0)], partition=0)
        assert leader.end_offset(T, 0) == 0  # NOTHING appended
        # acks=1 and acks=0 still work while the ISR forms
        assert client.produce_many(T, [(None, b"v1", 0)],
                                   partition=0, acks=1) == 0
        assert client.produce_many(T, [(None, b"v0", 0)],
                                   partition=0, acks=0) == -1  # masked
        assert leader.end_offset(T, 0) == 2
    finally:
        _teardown(srv, rs, client)


def test_invalid_required_acks_is_error_21():
    # wire error 21 (INVALID_REQUIRED_ACKS) surfaces TYPED — a
    # ValueError naming the legal acks values, not the generic
    # RuntimeError fallback (the protocol pass's P3 contract)
    leader, srv, rs = _leader_with_set()
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        with pytest.raises(ValueError, match="required_acks"):
            client.produce_many(T, [(None, b"v", 0)], partition=0,
                                acks=5)
        assert leader.end_offset(T, 0) == 0
    finally:
        _teardown(srv, rs, client)


def test_acks_all_commits_at_quorum_and_times_out_honestly():
    leader, srv, rs = _leader_with_set(max_lag_s=30.0)  # no eviction
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs)
        assert _quorum_produce(client, rs, [b"q0", b"q1"]) == 1
        # a frozen follower (no eviction in this window) stalls the
        # quorum: the produce APPENDS but times out un-acked
        rid = sorted(rs.followers)[0]
        rs.kill_follower(rid)
        with pytest.raises(ProduceTimedOutError):
            client.produce_many(T, [(None, b"stall", 0)], partition=0,
                                timeout_ms=300)
        assert leader.end_offset(T, 0) == 3  # appended, above the hwm
        assert rs.state.quorum_hwm(T, 0) == 2
    finally:
        _teardown(srv, rs, client)


def test_raw_produce_acks_all_quorum_and_rejection():
    from iotml.ops.framing import frame_entries

    leader, srv, rs = _leader_with_set()
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        frames = frame_entries([(None, b"raw0", 0), (None, b"raw1", 0)],
                               0)
        with pytest.raises(NotEnoughReplicasError):
            client.produce_raw(T, 0, frames)  # ISR not formed yet
        assert leader.end_offset(T, 0) == 0
        _form_isr(rs)
        result = {}

        def attempt():
            try:
                result["base"] = client.produce_raw(T, 0, frames)
            except Exception as e:  # noqa: BLE001
                result["err"] = e

        t = threading.Thread(target=attempt, daemon=True,
                             name="iotml-test-raw-quorum")
        t.start()
        deadline = time.monotonic() + 10
        while t.is_alive() and time.monotonic() < deadline:
            rs.sync_once()
            time.sleep(0.002)
        t.join(1.0)
        assert result.get("base") == 0, result
        # and the raw acks=1 leg skips the quorum wait entirely
        more = frame_entries([(None, b"raw2", 0)], 0)
        assert client.produce_raw(T, 0, more, acks=1) == 2
    finally:
        _teardown(srv, rs, client)


# --------------------------------------------------- the read barrier
def test_consumer_fetch_bounded_by_quorum_hwm():
    leader, srv, rs = _leader_with_set(max_lag_s=30.0)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs)
        _quorum_produce(client, rs, [b"v0", b"v1"])
        # an acks=1 tail past the quorum: invisible to consumers on
        # every read path until the followers mirror it
        client.produce_many(T, [(None, b"tail", 0)], partition=0,
                            acks=1)
        assert leader.end_offset(T, 0) == 3
        assert rs.state.quorum_hwm(T, 0) == 2
        # wire fetch: clamped, and the reported hwm IS the quorum hwm
        msgs = client.fetch(T, 0, 0, 100)
        assert [m.value for m in msgs] == [b"v0", b"v1"]
        assert client.last_hwm(T, 0) == 2
        # raw fetch: the frame batch is cut at the barrier
        raw = client.fetch_raw(T, 0, 0)
        from iotml.ops.framing import iter_frame_entries

        offs = [off for off, *_ in iter_frame_entries(raw.data)]
        assert offs == [0, 1]
        # in-process fetch on the leader broker: same barrier
        assert [m.value for m in leader.fetch(T, 0, 0, 100)] == \
            [b"v0", b"v1"]
        assert leader.fetch_raw(T, 0, 2) is None
        # the REPLICA path reads the tail (that is how it advances)
        assert [m.value for m in leader.fetch_tail(T, 0, 0, 100)] == \
            [b"v0", b"v1", b"tail"]
        # followers mirror -> the barrier advances -> tail readable
        for _ in range(10):
            rs.sync_once()
        assert [m.value for m in client.fetch(T, 0, 0, 100)] == \
            [b"v0", b"v1", b"tail"]
    finally:
        _teardown(srv, rs, client)


def test_truncate_frame_batch_cuts_at_frame_boundary():
    from iotml.ops.framing import frame_entries, truncate_frame_batch

    blob = frame_entries([(None, b"a", 0), (None, b"bb", 0),
                          (None, b"ccc", 0)], 10)
    cut = truncate_frame_batch(blob, 12)
    from iotml.ops.framing import iter_frame_entries

    assert [(off, v) for off, _k, v, _ts, _h
            in iter_frame_entries(cut)] == [(10, b"a"), (11, b"bb")]
    assert truncate_frame_batch(blob, 10) == b""
    assert truncate_frame_batch(blob, 99) == blob


# ------------------------------------------------ election + failover
def test_election_is_isr_restricted():
    leader, srv, rs = _leader_with_set(max_lag_s=0.2)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs)
        _quorum_produce(client, rs, [b"v0", b"v1", b"v2"])
        dead = sorted(rs.followers)[0]
        survivor = sorted(rs.followers)[1]
        rs.kill_follower(dead)
        # the log must GROW for the frozen follower to become stale —
        # a caught-up follower with nothing new to fetch stays in the
        # ISR legitimately (Kafka's rule too)
        client.produce_many(T, [(None, b"tail", 0)], partition=0,
                            acks=1)
        time.sleep(0.3)
        rs.sync_once()
        rs.state.evict_stale()
        assert rs.state.isr_follower_ids() == {survivor}
        # promoting the evicted follower is REFUSED
        with pytest.raises(RuntimeError, match="not in the ISR"):
            rs.promote(epoch=1, rid=dead)
        rid, addr = rs.promote(epoch=1)
        assert rid == survivor
        promoted = KafkaWireBroker(addr)
        assert [m.value for m in promoted.fetch(T, 0, 0, 100)] == \
            [b"v0", b"v1", b"v2", b"tail"]
        promoted.close()
    finally:
        _teardown(srv, rs, client)


def test_survivors_rejoin_isr_after_promotion():
    """A standalone ReplicaSet owns a private topology cell: after a
    promotion the NON-promoted survivors re-resolve the new leader
    through it and re-join the ISR — acks=all keeps working without
    any external wiring (the reviewed bug: they reconnect-looped
    against the dead leader's address forever)."""
    leader, srv, rs = _leader_with_set(n_followers=3, min_isr=2,
                                       max_lag_s=30.0)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs, width=4)
        _quorum_produce(client, rs, [b"v0", b"v1"])
        srv.kill()
        rid, addr = rs.promote(epoch=1)
        # two healthy survivors remain; they must re-point and re-join
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                len(rs.state.isr_follower_ids()) < 2:
            rs.sync_once()
        assert len(rs.state.isr_follower_ids()) == 2, \
            rs.state.isr_follower_ids()
        # and acks=all works against the promoted leader
        c2 = KafkaWireBroker(addr)
        assert _quorum_produce(c2, rs, [b"v2"]) == 2
        c2.close()
    finally:
        _teardown(srv, rs, client)


def test_cluster_topics_created_after_move_reach_the_new_leader():
    """create_topic after a failover/reassignment must land on the
    PROMOTED serving broker too (the reviewed bug: it answered
    UNKNOWN_TOPIC for every topic created after its shard moved)."""
    from iotml.cluster import ClusterController

    ctl = ClusterController(brokers=3, replication_factor=3, min_isr=2,
                            replica_sync="thread", max_lag_s=0.4)
    ctl.start()
    client = None
    try:
        ctl.create_topic(T, partitions=3)
        for i in range(3):
            assert ctl.replica_sets[i].await_isr(3, T, i, timeout_s=15)
        ctl.drain_broker(shard=1)
        ctl.create_topic("late-topic", partitions=3)
        client = ctl.client(client_id="late-topic-client")
        for attempt in range(5):
            try:
                client.produce("late-topic", b"x", partition=1)
                break
            except ConnectionError:
                time.sleep(0.2)
        assert len(client.fetch("late-topic", 1, 0, 10)) == 1
    finally:
        if client is not None:
            client.close()
        ctl.stop()


def test_no_isr_member_refuses_promotion():
    leader, srv, rs = _leader_with_set(max_lag_s=0.2)
    try:
        # nobody ever synced: promoting would serve a log with acked
        # records missing — refused outright
        with pytest.raises(RuntimeError, match="no in-sync replica"):
            rs.elect()
    finally:
        _teardown(srv, rs)


# ------------------------------------------------------- persistence
def test_hwm_persists_across_remount(tmp_path):
    from iotml.store.hwm import HwmFile

    store = str(tmp_path / "leader")
    leader, srv, rs = _leader_with_set(max_lag_s=30.0,
                                       hwm_file=HwmFile(store),
                                       store_dir=store)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs)
        _quorum_produce(client, rs, [b"v0", b"v1"])
        # an acks=1 tail the quorum never covered
        client.produce_many(T, [(None, b"unreplicated", 0)],
                            partition=0, acks=1)
        leader.flush()  # on disk but above the quorum mark
        rs.state.flush()
    finally:
        _teardown(srv, rs, client)
    # remount: crash recovery resurrects the whole log, but the read
    # barrier re-anchors at the persisted quorum HWM — consumers cannot
    # see the tail that was never replicated
    leader2 = Broker(store_dir=store)
    assert leader2.end_offset(T, 0) == 3
    state2 = ReplicationState(leader2, follower_ids=(999,),
                              min_isr=2, hwm_file=HwmFile(store))
    leader2.replication = state2
    assert state2.quorum_hwm(T, 0) == 2
    assert [m.value for m in leader2.fetch(T, 0, 0, 100)] == \
        [b"v0", b"v1"]
    # a re-formed quorum re-covers the tail and it becomes readable
    state2.observe_fetch(999, T, 0, 3)
    assert [m.value for m in leader2.fetch(T, 0, 0, 100)] == \
        [b"v0", b"v1", b"unreplicated"]
    leader2.close()


# -------------------------------------------------------- elasticity
def test_add_follower_bootstraps_via_raw_fetch_and_joins_isr():
    leader, srv, rs = _leader_with_set(n_followers=1, min_isr=1,
                                       max_lag_s=30.0)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs, width=2)
        client.produce_many(T, [(None, f"r{i}".encode(), 0)
                                for i in range(50)], partition=0,
                            acks=1)
        rid = rs.add_follower(sync="manual")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                rid not in rs.state.isr_follower_ids():
            rs.sync_once()
        assert rid in rs.state.isr_follower_ids()
        rep = rs.followers[rid]
        # the bootstrap rode the zero-copy mirror, byte-identical log
        assert rep.raw_mirrored == 50
        assert [m.value for m in rep.local.fetch(T, 0, 0, 100)] == \
            [m.value for m in leader.fetch(T, 0, 0, 100)]
        # retirement leaves the ISR first, quorum re-forms without it
        rs.retire_follower(rid)
        assert rid not in rs.state.isr_follower_ids()
        assert rid not in rs.followers
    finally:
        _teardown(srv, rs, client)


@pytest.mark.slow
def test_cluster_quorum_mode_add_and_drain_under_writes():
    """The cluster-level elasticity e2e (the drill runs it under
    sustained threaded load; this is the deterministic version)."""
    from iotml.cluster import ClusterController

    ctl = ClusterController(brokers=3, replication_factor=3, min_isr=2,
                            replica_sync="thread", max_lag_s=0.4)
    ctl.start()
    client = None
    try:
        ctl.create_topic(T, partitions=6)
        for i in range(3):
            assert ctl.replica_sets[i].await_isr(3, T, i, timeout_s=15)
        client = ctl.client(client_id="test-elastic")
        for p in range(6):
            client.produce(T, f"pre-{p}".encode(), partition=p)
        rep = ctl.add_broker(shard=1)
        assert rep["state"] == "retired"
        assert rep["raw_mirrored"] > 0  # zero-copy catch-up
        assert ctl.pmap.epoch(1) == 1
        # drain THROUGH the drained shard's own leader connection: the
        # deferred retirement must flush the admin response before the
        # old server dies
        wire = KafkaWireBroker(ctl.pmap.leader(2))
        drain = wire.cluster_admin("drain-broker", {"shard": 2})
        wire.close()
        assert drain["state"] == "retired"
        # the remaining followers re-point at each promoted leader
        # through the topology cell and RE-FORM the ISR — acks=all
        # (the default) is refused until min_isr holds again
        for i in (1, 2):
            assert ctl.replica_sets[i].state.await_isr(
                2, T, i, timeout_s=15), f"shard {i} ISR never re-formed"
        # the cluster serves reads and writes after both moves
        for p in range(6):
            for attempt in range(5):
                try:
                    client.produce(T, f"post-{p}".encode(), partition=p)
                    break
                except ConnectionError:
                    if attempt == 4:
                        raise
                    time.sleep(0.1)
        total = sum(len(client.fetch(T, p, 0, 100)) for p in range(6))
        assert total == 12
    finally:
        if client is not None:
            client.close()
        ctl.stop()


def test_cluster_admin_unsupported_without_controller():
    broker = Broker()
    srv = KafkaWireServer(broker).start()
    try:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        with pytest.raises(NotImplementedError):
            client.cluster_admin("status")
        client.close()
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------------- gauges
def test_replication_gauges_and_healthz_section():
    from iotml.obs import metrics as obs_metrics

    leader, srv, rs = _leader_with_set(max_lag_s=30.0)
    client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
    try:
        _form_isr(rs)
        assert obs_metrics.isr_size.value(topic=T, partition=0) == 3
        client.produce_many(T, [(None, b"v", 0)], partition=0, acks=1)
        rs.state.evict_stale()
        rendered = obs_metrics.default_registry.render()
        assert "iotml_isr_size" in rendered
        assert "iotml_under_replicated_partitions" in rendered
        assert "iotml_quorum_hwm_lag_records" in rendered
    finally:
        _teardown(srv, rs, client)
