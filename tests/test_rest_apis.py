"""Schema-Registry + Kafka-Connect REST surfaces.

Mirrors the reference's HTTP usage: `register_schema.py:20-31` (POST
/subjects/{s}/versions), console-consumer id resolution (GET /schemas/ids),
and the Connect workflows in `kafka-connect/mongodb/README.md:139-175` and
`gcs/README.md:21-43` (POST /connectors with connector.class configs,
status, delete)."""

import http.client
import json
import os

import pytest

from iotml.connect import ConnectServer, ConnectWorker
from iotml.core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
from iotml.stream import Broker, SchemaRegistry, SchemaRegistryServer


class Client:
    def __init__(self, server):
        self.conn = http.client.HTTPConnection(server.host, server.port,
                                               timeout=5)

    def req(self, method, path, body=None):
        payload = json.dumps(body) if body is not None else None
        self.conn.request(method, path, payload,
                          {"Content-Type": "application/json"})
        r = self.conn.getresponse()
        raw = r.read()
        return r.status, (json.loads(raw) if raw else None)


@pytest.fixture
def registry_api():
    reg = SchemaRegistry()
    server = SchemaRegistryServer(reg).start()
    yield Client(server), reg
    server.stop()


def test_registry_register_and_resolve(registry_api):
    api, reg = registry_api
    avsc = CAR_SCHEMA.avro_json()
    status, body = api.req("POST", "/subjects/sensor-data-value/versions",
                           {"schema": avsc})
    assert status == 200 and body["id"] >= 1
    sid = body["id"]

    # idempotent re-register (same fingerprint → same id)
    status, body2 = api.req("POST", "/subjects/sensor-data-value/versions",
                            {"schema": avsc})
    assert body2["id"] == sid

    status, body = api.req("GET", f"/schemas/ids/{sid}")
    assert status == 200
    assert json.loads(body["schema"])["name"] == "CarData"

    status, body = api.req("GET", "/subjects")
    assert body == ["sensor-data-value"]

    # second version under the subject
    api.req("POST", "/subjects/sensor-data-value/versions",
            {"schema": KSQL_CAR_SCHEMA.avro_json()})
    status, body = api.req("GET", "/subjects/sensor-data-value/versions")
    assert body == [1, 2]
    status, body = api.req("GET", "/subjects/sensor-data-value/versions/latest")
    assert body["version"] == 2
    status, body = api.req("GET", "/subjects/sensor-data-value/versions/1")
    assert body["id"] == sid

    # POST /subjects/{s}: is this schema registered here?
    status, body = api.req("POST", "/subjects/sensor-data-value",
                           {"schema": avsc})
    assert status == 200 and body["id"] == sid
    status, body = api.req("POST", "/subjects/other", {"schema": avsc})
    assert status == 404


def test_registry_error_paths(registry_api):
    api, _ = registry_api
    assert api.req("GET", "/schemas/ids/99")[0] == 404
    assert api.req("GET", "/subjects/nope/versions")[0] == 404
    assert api.req("POST", "/subjects/s/versions", {})[0] == 422
    assert api.req("POST", "/subjects/s/versions",
                   {"schema": "not json"})[0] == 422
    assert api.req("GET", "/bogus")[0] == 404


def test_connect_rest_filestream_to_document_twin(tmp_path):
    """The reference's two sink workflows driven purely over REST: CSV file →
    FileStreamSource → topic; topic → DocumentStoreSink (digital twin with
    HoistField$Key semantics)."""
    src_file = tmp_path / "feed.txt"
    src_file.write_text("")
    twin_path = str(tmp_path / "twin.json")

    broker = Broker()
    worker = ConnectWorker(broker)
    server = ConnectServer(worker, poll_interval_s=9999).start()  # manual pump
    try:
        api = Client(server)
        status, plugins = api.req("GET", "/connector-plugins")
        assert {p["class"] for p in plugins} == {
            "FileStreamSource", "DocumentStoreSink", "ObjectStoreSink"}

        status, body = api.req("POST", "/connectors", {
            "name": "csv-source",
            "config": {"connector.class":
                       "org.apache.kafka.connect.file.FileStreamSourceConnector",
                       "file": str(src_file), "topic": "car-data-csv"}})
        assert status == 201

        # the twin consumes the *keyed* stream (reference: topic sensor-data,
        # key = MQTT client id, HoistField$Key wraps it as _id)
        broker.create_topic("sensor-data")
        broker.produce("sensor-data", b'{"speed": 3.0}', key=b"car1")
        broker.produce("sensor-data", b'{"speed": 7.0}', key=b"car2")
        status, body = api.req("POST", "/connectors", {
            "name": "mongodb-twin",
            "config": {"connector.class":
                       "com.mongodb.kafka.connect.MongoSinkConnector",
                       "topics": "sensor-data", "path": twin_path,
                       "hoist.key.field": "_id"}})
        assert status == 201

        # duplicate create → 409, like Connect
        assert api.req("POST", "/connectors", {
            "name": "csv-source", "config": {
                "connector.class": "FileStreamSource",
                "file": str(src_file), "topic": "t"}})[0] == 409

        src_file.write_text('{"speed": 12.5}\n{"speed": 99.0}\n')
        server.pump_now()  # source drains the file
        server.pump_now()  # sink consumes the topic

        status, names = api.req("GET", "/connectors")
        assert names == ["csv-source", "mongodb-twin"]
        status, st = api.req("GET", "/connectors/mongodb-twin/status")
        assert st["connector"]["state"] == "RUNNING"
        assert st["tasks"][0]["records_processed"] == 2

        # twin materialized on disk, one document per car, key hoisted
        with open(twin_path) as fh:
            docs = json.load(fh)
        assert set(docs) == {"car1", "car2"}
        assert docs["car1"]["speed"] == 3.0
        # latest-state-wins upsert (digital-twin contract)
        broker.produce("sensor-data", b'{"speed": 8.0}', key=b"car1")
        server.pump_now()
        with open(twin_path) as fh:
            assert json.load(fh)["car1"]["speed"] == 8.0

        # delete → connector gone, worker no longer drives it
        status, _ = api.req("DELETE", "/connectors/csv-source")
        assert status == 204
        assert api.req("GET", "/connectors/csv-source")[0] == 404
        src_file.write_text('{"speed": 1}\n' * 3)
        counts = server.pump_now()
        assert "csv-source" not in counts
    finally:
        server.stop()


def test_connect_rest_object_store_sink(tmp_path):
    """GCS-style data-lake sink over REST: framed-Avro topic → .avro
    container files with the connector's object-naming scheme."""
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.ops.avro_container import read_container

    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=10, failure_rate=0.0))
    n = gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=30)
    lake = str(tmp_path / "lake")

    worker = ConnectWorker(broker)
    server = ConnectServer(worker, poll_interval_s=9999).start()
    try:
        api = Client(server)
        status, _ = api.req("POST", "/connectors", {
            "name": "gcs-lake",
            "config": {"connector.class":
                       "io.confluent.connect.gcs.GcsSinkConnector",
                       "topics": "SENSOR_DATA_S_AVRO", "directory": lake,
                       "flush.size": "100"}})
        assert status == 201
        server.pump_now()

        files = sorted(os.listdir(lake))
        assert files and all(f.startswith("SENSOR_DATA_S_AVRO+0+")
                             and f.endswith(".avro") for f in files)
        rows = 0
        for f in files:
            _, records = read_container(os.path.join(lake, f))
            rows += len(records)
        assert rows == n

        status, err = api.req("POST", "/connectors", {
            "name": "bad", "config": {"connector.class": "NopeConnector"}})
        assert status == 400 and "unknown connector.class" in err["message"]
    finally:
        server.stop()


def test_routes_ignore_query_strings(registry_api):
    """Confluent clients append query params (?normalize=false etc.);
    routing must match on the path alone."""
    api, _ = registry_api
    avsc = CAR_SCHEMA.avro_json()
    status, body = api.req(
        "POST", "/subjects/s-value/versions?normalize=false", {"schema": avsc})
    assert status == 200 and body["id"] >= 1
    status, body = api.req("GET", "/subjects?deleted=false")
    assert status == 200 and body == ["s-value"]


def test_check_with_invalid_schema_is_422(registry_api):
    api, _ = registry_api
    status, body = api.req("POST", "/subjects/s", {"schema": "not json"})
    assert status == 422
