"""fit_compiled (one-XLA-program scan) must match the per-step fit exactly."""

import jax
import numpy as np

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.train.loop import Trainer


def _batches(broker=None):
    broker = broker or Broker()
    gen = FleetGenerator(FleetScenario(num_cars=40, failure_rate=0.0))
    gen.publish(broker, "s", n_ticks=10)
    return SensorBatches(StreamConsumer(broker, ["s:0:0"]), batch_size=50,
                         only_normal=True)


def test_fit_compiled_matches_step_loop():
    t1 = Trainer(CAR_AUTOENCODER)
    h1 = t1.fit(_batches(), epochs=3)
    t2 = Trainer(CAR_AUTOENCODER)
    # fused="never": this test pins the *scan* path to the step loop
    # bitwise; the fused Pallas path has its own tolerance-based parity
    # tests in test_fused_train.py
    h2 = t2.fit_compiled(_batches(), epochs=3, fused="never")
    np.testing.assert_allclose(h1["loss"], h2["loss"], rtol=1e-5, atol=1e-7)
    for a, b in zip(jax.tree.leaves(jax.device_get(t1.state.params)),
                    jax.tree.leaves(jax.device_get(t2.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    assert int(t2.state.step) == int(t1.state.step)


def test_fit_compiled_empty_stream():
    broker = Broker()
    broker.create_topic("empty")
    bs = SensorBatches(StreamConsumer(broker, ["empty:0:0"]), batch_size=10)
    hist = Trainer(CAR_AUTOENCODER).fit_compiled(bs, epochs=2)
    assert hist["loss"] == []
