"""Schema derivation: both Avro variants must match the reference's files."""

import json

import numpy as np

from tests.conftest import requires_reference, REFERENCE_ROOT
from iotml.core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA, CSV_COLUMNS


def test_producer_schema_shape():
    assert CAR_SCHEMA.num_sensors == 18
    assert CAR_SCHEMA.label_field is None
    assert CAR_SCHEMA.field_names[0] == "coolant_temp"
    assert CAR_SCHEMA.field_names[-1] == "control_unit_firmware"


def test_ksql_schema_shape():
    assert len(KSQL_CAR_SCHEMA.fields) == 19
    assert KSQL_CAR_SCHEMA.num_sensors == 18
    assert KSQL_CAR_SCHEMA.label_field == "FAILURE_OCCURRED"
    # KSQL name collapsing quirk
    names = KSQL_CAR_SCHEMA.field_names
    assert "TIRE_PRESSURE11" in names
    assert "ACCELEROMETER11_VALUE" in names
    assert all(f.nullable for f in KSQL_CAR_SCHEMA.fields)


def test_avro_json_roundtrips():
    parsed = json.loads(CAR_SCHEMA.avro_json())
    assert parsed["name"] == "CarData"
    assert len(parsed["fields"]) == 18
    parsed = json.loads(KSQL_CAR_SCHEMA.avro_json())
    assert parsed["fields"][-1]["name"] == "FAILURE_OCCURRED"
    assert parsed["fields"][0]["type"] == ["null", "double"]


@requires_reference
def test_schema_matches_reference_avsc():
    """Field names/types/order must match the reference .avsc byte-for-intent."""
    with open(f"{REFERENCE_ROOT}/testdata/cardata-v1.avsc") as f:
        ref = json.load(f)
    ours = json.loads(CAR_SCHEMA.avro_json())
    assert [f["name"] for f in ref["fields"]] == [f["name"] for f in ours["fields"]]
    assert [f["type"] for f in ref["fields"]] == [f["type"] for f in ours["fields"]]

    with open(f"{REFERENCE_ROOT}/python-scripts/AUTOENCODER-TensorFlow-IO-Kafka/cardata-v1.avsc") as f:
        ref = json.load(f)
    ours = json.loads(KSQL_CAR_SCHEMA.avro_json())
    assert [f["name"] for f in ref["fields"]] == [f["name"] for f in ours["fields"]]
    assert [f["type"] for f in ref["fields"]] == [f["type"] for f in ours["fields"]]


@requires_reference
def test_csv_columns_match_reference_fixture():
    with open(f"{REFERENCE_ROOT}/testdata/car-sensor-data.csv") as f:
        header = f.readline().strip().split(",")
    assert tuple(header) == CSV_COLUMNS


def test_numpy_dtypes():
    assert CAR_SCHEMA.field("speed").np_dtype == np.float32
    assert CAR_SCHEMA.field("tire_pressure_1_1").np_dtype == np.int32
    assert KSQL_CAR_SCHEMA.field("SPEED").np_dtype == np.float64
