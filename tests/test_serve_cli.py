"""Serve CLI: long-lived scoring over the wire, committed-offset resume."""

import numpy as np

from iotml.cli.cardata import main as cardata_main
from iotml.cli.serve import main as serve_main
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.stream.broker import Broker
from iotml.stream.kafka_wire import KafkaWireServer


def _train_model(backing, root):
    gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
    gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=110)  # 11k records
    with KafkaWireServer(backing) as srv:
        assert cardata_main([f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO",
                             "0", "model-predictions", "train", "model1",
                             root, "--train.epochs=1"]) == 0


def test_serve_scores_and_resumes(tmp_path):
    root = str(tmp_path / "artifacts")
    backing = Broker()
    _train_model(backing, root)
    with KafkaWireServer(backing) as srv:
        argv = [f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO", "committed",
                "model-predictions", "model1", root,
                "--serve.poll_interval_s=0.01", "--serve.threshold=5"]
        assert serve_main(argv, max_rounds=3) == 0
        n1 = backing.end_offset("model-predictions", 0)
        assert n1 == 11_000
        # verdict suffix present (threshold configured)
        msg = backing.fetch("model-predictions", 0, 0, 1)[0].value.decode()
        assert "|normal|" in msg or "|anomaly|" in msg

        # restart: new records arrive; committed offsets mean only THEY are
        # scored (no re-scoring of the first 11k)
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=5)  # +500
        assert serve_main(argv, max_rounds=2) == 0
        n2 = backing.end_offset("model-predictions", 0)
        assert n2 == n1 + 500


def test_serve_usage_error(capsys):
    assert serve_main(["too", "few"]) == 1
    assert "usage" in capsys.readouterr().out


def test_serve_group_mode_elastic_over_wire(tmp_path):
    """offset='group': two scorer replicas (separate wire clients) join the
    serve group, split partitions disjointly, and together score the whole
    stream — the reference's scalable predict Deployment, elastic."""
    import threading

    from iotml.cli import serve as serve_cli
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.train.checkpoint import CheckpointManager
    from iotml.train.loop import TrainState
    import jax
    import numpy as np

    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=40, failure_rate=0.0))
    n = gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=50, partitions=4)
    broker.create_topic("model-predictions", partitions=1)

    # store a model the scorers can download
    state = TrainState.create(CAR_AUTOENCODER, jax.random.PRNGKey(0),
                              np.zeros((1, 18), np.float32))
    root = str(tmp_path / "store")
    ckpt = CheckpointManager(str(tmp_path / "ck")).save(state, cursors=[])
    from iotml.train.artifacts import ArtifactStore
    ArtifactStore(root).upload_tree(ckpt, "m1")

    with KafkaWireServer(broker) as srv:
        args = [f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO", "group",
                "model-predictions", "m1", root]
        rcs = [None, None]

        def run(i):
            rcs[i] = serve_cli.main(list(args), max_rounds=6)

        t1 = threading.Thread(target=run, args=(0,))
        t2 = threading.Thread(target=run, args=(1,))
        t1.start(); t2.start()
        t1.join(timeout=120); t2.join(timeout=120)
        assert rcs == [0, 0]

    # every partition fully consumed AND committed by the group (complete
    # coverage + resumability); the scored count may exceed n because a
    # rebalance mid-drain redelivers uncommitted records (at-least-once)
    for p in range(4):
        assert broker.committed("iotml-serve", "SENSOR_DATA_S_AVRO", p) == \
            broker.end_offset("SENSOR_DATA_S_AVRO", p)
    scored = broker.end_offset("model-predictions", 0)
    assert n <= scored <= 2 * n
