"""Serve CLI: long-lived scoring over the wire, committed-offset resume."""

import numpy as np

from iotml.cli.cardata import main as cardata_main
from iotml.cli.serve import main as serve_main
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.stream.broker import Broker
from iotml.stream.kafka_wire import KafkaWireServer


def _train_model(backing, root):
    gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
    gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=110)  # 11k records
    with KafkaWireServer(backing) as srv:
        assert cardata_main([f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO",
                             "0", "model-predictions", "train", "model1",
                             root, "--train.epochs=1"]) == 0


def test_serve_scores_and_resumes(tmp_path):
    root = str(tmp_path / "artifacts")
    backing = Broker()
    _train_model(backing, root)
    with KafkaWireServer(backing) as srv:
        argv = [f"127.0.0.1:{srv.port}", "SENSOR_DATA_S_AVRO", "committed",
                "model-predictions", "model1", root,
                "--serve.poll_interval_s=0.01", "--serve.threshold=5"]
        assert serve_main(argv, max_rounds=3) == 0
        n1 = backing.end_offset("model-predictions", 0)
        assert n1 == 11_000
        # verdict suffix present (threshold configured)
        msg = backing.fetch("model-predictions", 0, 0, 1)[0].value.decode()
        assert "|normal|" in msg or "|anomaly|" in msg

        # restart: new records arrive; committed offsets mean only THEY are
        # scored (no re-scoring of the first 11k)
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        gen.publish(backing, "SENSOR_DATA_S_AVRO", n_ticks=5)  # +500
        assert serve_main(argv, max_rounds=2) == 0
        n2 = backing.end_offset("model-predictions", 0)
        assert n2 == n1 + 500


def test_serve_usage_error(capsys):
    assert serve_main(["too", "few"]) == 1
    assert "usage" in capsys.readouterr().out
