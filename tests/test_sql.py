"""KSQL-equivalent SQL dialect + REST server.

Mirrors the reference's actual KSQL usage: the four-object DDL pipeline
(`01_installConfluentPlatform.sh:229-258`), `PRINT 'topic' FROM BEGINNING`
(`infrastructure/confluent/README.md:99`), SHOW/DESCRIBE/TERMINATE/DROP
lifecycle, and REST POSTs to /ksql + /query."""

import http.client
import json

import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.ops.avro import AvroCodec
from iotml.ops.framing import unframe
from iotml.stream.broker import Broker
from iotml.streamproc import (KsqlServer, SqlEngine, SqlError,
                              install_reference_pipeline)


def _json_record(car: int, speed: float = 10.0, failure: str = "false"):
    rec = {
        "coolant_temp": 90.0, "intake_air_temp": 25.0,
        "intake_air_flow_speed": 20.0, "battery_percentage": 70.0,
        "battery_voltage": 380.0, "current_draw": 20.0, "speed": speed,
        "engine_vibration_amplitude": speed * 100, "throttle_pos": 0.5,
        "tire_pressure_1_1": 30, "tire_pressure_1_2": 30,
        "tire_pressure_2_1": 31, "tire_pressure_2_2": 31,
        "accelerometer_1_1_value": 2.0, "accelerometer_1_2_value": 2.0,
        "accelerometer_2_1_value": 2.0, "accelerometer_2_2_value": 2.0,
        "control_unit_firmware": 1000, "failure_occurred": failure,
    }
    return json.dumps(rec).encode()


def _produce_fleet(broker, n_cars=4, per_car=6):
    broker.create_topic("sensor-data", partitions=2)
    for c in range(n_cars):
        key = f"car{c}".encode()
        for i in range(per_car):
            broker.produce("sensor-data", _json_record(c, speed=float(i)),
                           key=key, timestamp_ms=i * 60_000)


def test_reference_pipeline_ddl_end_to_end():
    broker = Broker()
    _produce_fleet(broker)
    engine = SqlEngine(broker)
    results = install_reference_pipeline(engine)
    assert all(r.get("commandStatus", {}).get("status") == "SUCCESS"
               for r in results)
    emitted = engine.pump()
    assert emitted > 0

    # The AVRO output topic must be byte-compatible with what the ML ingest
    # layer decodes (KSQL_CAR_SCHEMA, Confluent-framed) — the load-bearing
    # contract of the reference's KSQL stage.
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    msgs = broker.fetch("SENSOR_DATA_S_AVRO", 0, 0, max_messages=10)
    assert msgs, "CSAS produced nothing on partition 0"
    sid, payload = unframe(msgs[0].value)
    rec = codec.decode(payload)
    assert rec["INTAKE_AIR_TEMP"] == 25.0
    assert rec["FAILURE_OCCURRED"] == "false"
    assert sid == engine.registry.latest("SENSOR_DATA_S_AVRO-value").schema_id

    # REKEY: messages keyed by car id.
    spec = broker.topic("SENSOR_DATA_S_AVRO_REKEY")
    keys = set()
    for p in range(spec.partitions):
        for m in broker.fetch("SENSOR_DATA_S_AVRO_REKEY", p, 0, 1000):
            keys.add(m.key)
    assert keys == {b"car0", b"car1", b"car2", b"car3"}

    # CTAS tumbling 5-min count: 6 records/car at minutes 0..5 ⇒ windows
    # [0,5min) holds 5 and [5min,10min) holds 1, per car.
    table = engine.table("SENSOR_DATA_EVENTS_PER_5MIN_T")
    assert table[("car0", 0)]["EVENT_COUNT"] == 5
    assert table[("car0", 300_000)]["EVENT_COUNT"] == 1


def test_pump_is_incremental_and_resumable():
    broker = Broker()
    _produce_fleet(broker, n_cars=1, per_car=3)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    engine.pump()
    n0 = broker.end_offset("SENSOR_DATA_S_AVRO", 0) + \
        broker.end_offset("SENSOR_DATA_S_AVRO", 1)
    engine.pump()  # no new input ⇒ no duplicate output
    n1 = broker.end_offset("SENSOR_DATA_S_AVRO", 0) + \
        broker.end_offset("SENSOR_DATA_S_AVRO", 1)
    assert n1 == n0 == 3
    broker.produce("sensor-data", _json_record(0), key=b"car0")
    engine.pump()
    n2 = broker.end_offset("SENSOR_DATA_S_AVRO", 0) + \
        broker.end_offset("SENSOR_DATA_S_AVRO", 1)
    assert n2 == 4


def test_where_filter_and_expressions():
    broker = Broker()
    broker.create_topic("t", partitions=1)
    for i in range(10):
        broker.produce("t", json.dumps({"v": i, "label": "odd" if i % 2 else
                                        "even"}).encode(), key=b"k")
    engine = SqlEngine(broker)
    engine.execute("CREATE STREAM S (V DOUBLE, LABEL STRING) "
                   "WITH (KAFKA_TOPIC='t', VALUE_FORMAT='JSON');")
    engine.execute("CREATE STREAM EVENS AS SELECT V, V * 2 AS DOUBLED "
                   "FROM S WHERE LABEL = 'even' AND V >= 2;")
    engine.pump()
    rows = engine.execute("SELECT V, DOUBLED FROM EVENS;")[0]["rows"]
    assert [r[0] for r in rows] == [2, 4, 6, 8]
    assert [r[1] for r in rows] == [4, 8, 12, 16]


def test_show_describe_terminate_drop_lifecycle():
    broker = Broker()
    broker.create_topic("t", partitions=1)
    engine = SqlEngine(broker)
    engine.execute("CREATE STREAM S (V DOUBLE) WITH (KAFKA_TOPIC='t');")
    engine.execute("CREATE STREAM S2 AS SELECT V FROM S;")
    assert {s["name"] for s in engine.execute("SHOW STREAMS;")[0]["streams"]} \
        == {"S", "S2"}
    queries = engine.execute("SHOW QUERIES;")[0]["queries"]
    assert len(queries) == 1 and queries[0]["id"].startswith("CSAS_S2")
    desc = engine.execute("DESCRIBE S2;")[0]["sourceDescription"]
    assert desc["fields"] == [{"name": "V", "type": "DOUBLE"}]

    # KSQL semantics: can't drop a stream a live query writes into
    with pytest.raises(SqlError):
        engine.execute("DROP STREAM S2;")
    # ... nor one a live query reads from
    with pytest.raises(SqlError):
        engine.execute("DROP STREAM S;")
    engine.execute(f"TERMINATE {queries[0]['id']};")
    engine.execute("DROP STREAM S2;")
    engine.execute("DROP STREAM S;")
    assert engine.execute("SHOW STREAMS;")[0]["streams"] == []
    # idempotent teardown, as the reference's delete script replays DDL
    engine.execute("DROP STREAM IF EXISTS S2;")


def test_print_topic_from_beginning():
    broker = Broker()
    _produce_fleet(broker, n_cars=1, per_car=2)
    engine = SqlEngine(broker)
    res = engine.execute("PRINT 'sensor-data' FROM BEGINNING LIMIT 2;")[0]
    assert res["topic"] == "sensor-data"
    assert len(res["rows"]) == 2
    assert json.loads(res["rows"][0]["value"])["speed"] == 0.0


def test_bad_statements_raise():
    engine = SqlEngine(Broker())
    for bad in ("FROB THE STREAM;", "CREATE STREAM X AS SELECT * FROM NOPE;",
                "SELECT * FROM MISSING;", "TERMINATE NOPE;"):
        with pytest.raises(SqlError):
            engine.execute(bad)


def test_rest_server_ksql_and_query():
    broker = Broker()
    _produce_fleet(broker, n_cars=2, per_car=3)
    engine = SqlEngine(broker)
    server = KsqlServer(engine, pump_interval_s=0.01).start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port, timeout=5)

        def post(path, sql):
            conn.request("POST", path, json.dumps({"ksql": sql}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, r.read()

        status, body = post("/ksql", "SHOW TOPICS;")
        assert status == 200
        assert any(t["name"] == "sensor-data"
                   for t in json.loads(body)[0]["topics"])

        from iotml.streamproc.sql import REFERENCE_PIPELINE_DDL
        status, body = post("/ksql", REFERENCE_PIPELINE_DDL)
        assert status == 200 and len(json.loads(body)) == 4
        server.pump_now()

        status, body = post("/query",
                            "SELECT ROWKEY, SPEED FROM SENSOR_DATA_S_AVRO "
                            "WHERE SPEED >= 1 LIMIT 3;")
        assert status == 200
        lines = [json.loads(x) for x in body.decode().splitlines()]
        assert lines[0]["header"] == ["ROWKEY", "SPEED"]
        assert len(lines) == 4  # header + 3 rows

        status, body = post("/ksql", "BOGUS;")
        assert status == 400
        assert json.loads(body)["@type"] == "statement_error"

        conn.request("GET", "/healthcheck")
        assert json.loads(conn.getresponse().read())["isHealthy"] is True
    finally:
        server.stop()


def test_sql_output_feeds_training_batches():
    """The full L4→L5 contract: KSQL-equivalent output is directly consumable
    by the ML data layer (SensorBatches), as in the reference where the
    training pod reads the CSAS topic (`model-training.yaml:15`)."""
    from iotml.data.dataset import SensorBatches
    from iotml.stream.consumer import StreamConsumer

    broker = Broker()
    _produce_fleet(broker, n_cars=3, per_car=40)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    engine.pump()

    spec = broker.topic("SENSOR_DATA_S_AVRO")
    consumer = StreamConsumer(
        broker, [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)],
        group="sql-train")
    batches = SensorBatches(consumer, batch_size=32, only_normal=True)
    batch = next(iter(batches))
    assert batch.x.shape == (32, 18)
    assert batch.n_valid == 32


def test_unaliased_expressions_get_unique_auto_names():
    broker = Broker()
    broker.create_topic("t", partitions=1)
    broker.produce("t", json.dumps({"v": 10}).encode(), key=b"k")
    engine = SqlEngine(broker)
    engine.execute("CREATE STREAM S (V DOUBLE) "
                   "WITH (KAFKA_TOPIC='t', VALUE_FORMAT='JSON');")
    engine.execute("CREATE STREAM D AS SELECT V + 1, V - 1 FROM S;")
    engine.pump()
    desc = engine.execute("DESCRIBE D;")[0]["sourceDescription"]
    names = [f["name"] for f in desc["fields"]]
    assert len(set(names)) == 2  # no silent column collision
    row = json.loads(broker.fetch("D", 0, 0)[0].value)
    assert sorted(row.values()) == [9, 11]


def test_ctas_aggregate_state_survives_engine_restart():
    """The CTAS output topic is the table's changelog: a restarted engine
    rebuilds aggregate state from it instead of undercounting."""
    broker = Broker()
    _produce_fleet(broker, n_cars=2, per_car=4)
    e1 = SqlEngine(broker)
    install_reference_pipeline(e1)
    e1.pump()
    t1 = e1.table("SENSOR_DATA_EVENTS_PER_5MIN_T")
    assert t1[("car0", 0)]["EVENT_COUNT"] == 4

    # more records arrive while the "server" is down
    for i in range(3):
        broker.produce("sensor-data", _json_record(0), key=b"car0",
                       timestamp_ms=i * 60_000)

    e2 = SqlEngine(broker)  # fresh process, same broker
    install_reference_pipeline(e2)
    e2.pump()
    t2 = e2.table("SENSOR_DATA_EVENTS_PER_5MIN_T")
    assert t2[("car0", 0)]["EVENT_COUNT"] == 7  # 4 restored + 3 new


def test_rest_rejects_non_object_bodies_gracefully():
    engine = SqlEngine(Broker())
    server = KsqlServer(engine, pump_interval_s=9999).start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port, timeout=5)
        for payload in ('[1,2,3]', '42'):
            conn.request("POST", "/ksql", payload,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 400
            r.read()
        # a bare SQL string body is accepted as a convenience
        conn.request("POST", "/ksql", '"SHOW STREAMS;"',
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 200
        assert json.loads(r.read())[0]["streams"] == []
    finally:
        server.stop()


def test_ctas_recreate_after_drop_does_not_double_count():
    """TERMINATE + DROP (topic retained) + re-CREATE must not seed restored
    changelog state AND replay input from offset zero."""
    broker = Broker()
    _produce_fleet(broker, n_cars=1, per_car=4)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    engine.pump()
    assert engine.table("SENSOR_DATA_EVENTS_PER_5MIN_T")[("car0", 0)][
        "EVENT_COUNT"] == 4

    qid = next(q for q in engine.queries if q.startswith("CTAS"))
    engine.execute(f"TERMINATE {qid};")
    engine.execute("DROP TABLE SENSOR_DATA_EVENTS_PER_5MIN_T;")
    engine.execute(
        "CREATE TABLE SENSOR_DATA_EVENTS_PER_5MIN_T AS "
        "SELECT ROWKEY AS CAR, COUNT(*) AS EVENT_COUNT "
        "FROM SENSOR_DATA_S_AVRO_REKEY "
        "WINDOW TUMBLING (SIZE 5 MINUTES) GROUP BY ROWKEY;")
    engine.pump()
    # stable consumer group ⇒ committed offsets + restored state line up
    assert engine.table("SENSOR_DATA_EVENTS_PER_5MIN_T")[("car0", 0)][
        "EVENT_COUNT"] == 4


def test_ctas_recreate_with_different_sql_starts_fresh():
    """A re-created table with DIFFERENT semantics must not inherit the old
    query's committed offsets or changelog state (group id is fingerprinted
    by statement text)."""
    broker = Broker()
    _produce_fleet(broker, n_cars=1, per_car=4)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    engine.pump()
    qid = next(q for q in engine.queries if q.startswith("CTAS"))
    engine.execute(f"TERMINATE {qid};")
    engine.execute("DROP TABLE SENSOR_DATA_EVENTS_PER_5MIN_T;")

    # same sink name, different aggregation: SUM of SPEED, not COUNT
    engine.execute(
        "CREATE TABLE SENSOR_DATA_EVENTS_PER_5MIN_T "
        "WITH (KAFKA_TOPIC='T2') AS "
        "SELECT ROWKEY AS CAR, SUM(SPEED) AS TOTAL_SPEED "
        "FROM SENSOR_DATA_S_AVRO_REKEY "
        "WINDOW TUMBLING (SIZE 5 MINUTES) GROUP BY ROWKEY;")
    engine.pump()
    table = engine.table("SENSOR_DATA_EVENTS_PER_5MIN_T")
    # speeds were 0,1,2,3 → sum 6; inherited COUNT state would give 4 or 10
    assert table[("car0", 0)] == {"TOTAL_SPEED": 6.0}


def test_parser_fuzz_never_crashes():
    """Arbitrary garbage must come back as SqlError (the REST 400), never
    an unhandled exception — the server's statement_error contract."""
    import random

    rng = random.Random(7)
    words = ["CREATE", "STREAM", "TABLE", "SELECT", "FROM", "WHERE", "AS",
             "GROUP", "BY", "WINDOW", "TUMBLING", "SIZE", "(", ")", ",",
             ";", "*", "+", "-", "/", "=", "'x'", "5", "5.5", "COUNT",
             "S", "V", "DOUBLE", "WITH", "KAFKA_TOPIC", "PARTITION",
             "DROP", "TERMINATE", "PRINT", "SHOW", "'q u o t e d'", "<>",
             "IS", "NULL", "NOT", "AND", "OR", "LIMIT", "EMIT", "CHANGES"]
    broker = Broker()
    broker.create_topic("t", partitions=1)
    engine = SqlEngine(broker)
    engine.execute("CREATE STREAM S (V DOUBLE) WITH (KAFKA_TOPIC='t');")
    crashed = []
    for _ in range(500):
        stmt = " ".join(rng.choices(words, k=rng.randint(1, 14)))
        try:
            engine.execute(stmt)
        except SqlError:
            pass
        except Exception as e:  # pragma: no cover - the failure we hunt
            crashed.append((stmt, repr(e)))
    assert not crashed, crashed[:3]
    # the engine still works afterwards
    engine.pump()
    assert engine.execute("SHOW STREAMS;")[0]["streams"]


def test_csas_rejects_unknown_value_format():
    """ADVICE r1: an unsupported CSAS/CTAS VALUE_FORMAT must 4xx at CREATE
    time, not silently write JSON and decode to nothing downstream."""
    broker = Broker()
    _produce_fleet(broker, n_cars=1, per_car=1)
    engine = SqlEngine(broker)
    engine.execute(
        "CREATE STREAM S (SPEED DOUBLE, FAILURE_OCCURRED VARCHAR) "
        "WITH (KAFKA_TOPIC='sensor-data', VALUE_FORMAT='JSON');")
    with pytest.raises(SqlError, match="VALUE_FORMAT"):
        engine.execute(
            "CREATE STREAM S2 WITH (VALUE_FORMAT='PROTOBUF') "
            "AS SELECT SPEED FROM S;")


def test_pump_isolates_poisoned_query():
    """ADVICE r1: one query whose task raises must not starve the queries
    after it.  The error is surfaced via SHOW QUERIES, the consumer cursor
    is rewound so the failed chunk is RETRIED (not silently skipped), and
    recovery reprocesses every record."""
    broker = Broker()
    _produce_fleet(broker, n_cars=2, per_car=3)  # 6 records
    engine = SqlEngine(broker)
    engine.execute(
        "CREATE STREAM S (SPEED DOUBLE, FAILURE_OCCURRED VARCHAR) "
        "WITH (KAFKA_TOPIC='sensor-data', VALUE_FORMAT='JSON');")
    engine.execute("CREATE STREAM A AS SELECT SPEED FROM S;")
    engine.execute("CREATE STREAM B AS SELECT SPEED FROM S;")
    qa, qb = list(engine.queries.values())

    # poison process() AFTER the poll: the cursor has already advanced when
    # the failure hits — exactly the lost-chunk scenario
    real_process = qa.task.process

    def poisoned(messages):
        raise RuntimeError("avro encode type mismatch")

    qa.task.process = poisoned
    n = engine.pump()
    assert n > 0, "healthy query B must still emit"
    shown = engine.execute("SHOW QUERIES;")[0]["queries"]
    states = {q["id"]: q for q in shown}
    assert states[qa.query_id]["state"] == "ERROR"
    assert "avro encode type mismatch" in states[qa.query_id]["error"]
    assert states[qb.query_id]["state"] == "RUNNING"

    # the error stays visible across pumps while the chunk keeps failing
    # (an empty successful poll must NOT clear it, because the cursor was
    # rewound and the same records keep being retried)
    engine.pump()
    shown = engine.execute("SHOW QUERIES;")[0]["queries"]
    assert {q["id"]: q for q in shown}[qa.query_id]["state"] == "ERROR"

    # recovery: the task stops raising -> the rewound chunk reprocesses,
    # nothing was lost, and the error clears
    qa.task.process = real_process
    engine.pump()
    shown = engine.execute("SHOW QUERIES;")[0]["queries"]
    assert all(q["state"] == "RUNNING" for q in shown)
    a_out = []
    for part in range(broker.topic("A").partitions):
        a_out.extend(broker.fetch("A", part, 0, 100))
    assert len(a_out) == 6, "all records recovered after the poisoned rounds"


def test_ctas_aggregate_state_rolls_back_on_poisoned_chunk():
    """Rewind-and-retry must not double-count: a CTAS chunk that fails
    after folding records into the accumulators rolls its state back, so
    retries are idempotent and the final COUNT is exact."""
    broker = Broker()
    _produce_fleet(broker, n_cars=2, per_car=3)  # 6 records
    engine = SqlEngine(broker)
    engine.execute(
        "CREATE STREAM S (CAR VARCHAR, SPEED DOUBLE) "
        "WITH (KAFKA_TOPIC='sensor-data', VALUE_FORMAT='JSON', KEY='CAR');")
    engine.execute(
        "CREATE TABLE T AS SELECT ROWKEY AS CAR, COUNT(*) AS N "
        "FROM S GROUP BY ROWKEY;")
    (q,) = engine.queries.values()

    # raise while BUILDING output rows — after _update mutated the slots
    real = q.task._changelog_row
    q.task._changelog_row = lambda slot, row: (_ for _ in ()).throw(
        RuntimeError("encode failure"))
    engine.pump()
    engine.pump()  # retry fails again; state must not accumulate
    assert q.error and "encode failure" in q.error

    q.task._changelog_row = real
    engine.pump()
    table = engine.table("T")
    counts = {k[0]: v["N"] for k, v in table.items()}
    assert counts == {"car0": 3, "car1": 3}, \
        f"retries must not double-count, got {counts}"


def test_poisoned_later_chunk_does_not_reemit_earlier_chunks():
    """Per-chunk offset commits bound retry re-emission to the failed
    chunk: a healthy first chunk is emitted once, not once per pump."""
    broker = Broker()
    broker.create_topic("t", partitions=1)
    for i in range(8):
        broker.produce("t", json.dumps({"V": float(i)}).encode(), key=b"k")
    engine = SqlEngine(broker)
    engine.execute("CREATE STREAM S (V DOUBLE) "
                   "WITH (KAFKA_TOPIC='t', VALUE_FORMAT='JSON');")
    engine.execute("CREATE STREAM OUT AS SELECT V FROM S;")
    (q,) = engine.queries.values()

    real_process = q.task.process

    def poison_high(messages):
        rows = real_process(messages)
        if any(json.loads(v)["V"] >= 4.0 for _, v, _ in rows):
            raise RuntimeError("poison in chunk 2")
        return rows

    q.task.process = poison_high
    engine.pump(chunk=4)   # chunk 1 (V 0-3) emits + commits; chunk 2 raises
    n_after_first = broker.end_offset("OUT", 0)
    assert n_after_first == 4
    engine.pump(chunk=4)   # retries ONLY chunk 2; chunk 1 must not re-emit
    engine.pump(chunk=4)
    assert broker.end_offset("OUT", 0) == 4, "earlier chunk re-emitted"

    q.task.process = real_process
    engine.pump(chunk=4)
    assert broker.end_offset("OUT", 0) == 8
    assert q.error is None


def test_csas_native_encode_byte_parity():
    """The native batch encoder must emit byte-identical framed Avro to the
    pure-python codec for the JSON→AVRO CSAS — and long/None string values
    must fall back to the python path, not truncate."""
    pytest.importorskip("iotml.stream.native")
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.stream.native import NativeCodec

    try:
        NativeCodec(KSQL_CAR_SCHEMA)
    except Exception:
        pytest.skip("native engine unavailable")

    broker = Broker()
    _produce_fleet(broker, n_cars=3, per_car=5)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    (q,) = [q for q in engine.queries.values()
            if q.sink == "SENSOR_DATA_S_AVRO"]
    assert q.task._native_sink is not None, "native encode path not active"
    engine.pump()

    codec = AvroCodec(KSQL_CAR_SCHEMA)
    n_checked = 0
    for p in range(broker.topic("SENSOR_DATA_S_AVRO").partitions):
        for m in broker.fetch("SENSOR_DATA_S_AVRO", p, 0, 1000):
            sid, payload = unframe(m.value)
            rec = codec.decode(payload)
            # python re-encode of the decoded record reproduces the bytes
            assert codec.encode(rec) == payload
            assert sid == q.task.sink_schema_id
            n_checked += 1
    assert n_checked == 15

    # fallback: a record whose string field exceeds the native label
    # stride still round-trips (python path)
    long_rec = json.loads(_json_record(0))
    long_rec["failure_occurred"] = "a-very-long-failure-label-exceeding-stride"
    broker.produce("sensor-data", json.dumps(long_rec).encode(), key=b"car0")
    engine.pump()
    total = sum(broker.end_offset("SENSOR_DATA_S_AVRO", p)
                for p in range(broker.topic("SENSOR_DATA_S_AVRO").partitions))
    assert total == 16


def test_native_decode_exactness_fallbacks():
    """The native AVRO fast paths must yield to the python codec whenever
    exactness is at risk: non-ASCII strings (numpy U-cast), and int/long
    beyond the float64-exact range (2^53)."""
    pytest.importorskip("iotml.stream.native")
    broker = Broker()
    broker.create_topic("src", partitions=1)
    engine = SqlEngine(broker)
    engine.execute(
        "CREATE STREAM S (BIGNUM BIGINT, NOTE STRING) "
        "WITH (KAFKA_TOPIC='src', VALUE_FORMAT='AVRO');")
    engine.execute(
        "CREATE STREAM OUT WITH (VALUE_FORMAT='AVRO') "
        "AS SELECT BIGNUM, NOTE FROM S;")
    meta = engine.sources["S"]
    codec = AvroCodec(meta.record_schema())

    big = 2 ** 53 + 1           # float64 cannot represent this exactly
    vals = [(big, "café"),      # non-ASCII → U-cast fallback
            (7, "plain"),
            (big, "plain")]     # big int → exactness fallback
    from iotml.ops.framing import frame as _frame
    for b, s in vals:
        payload = codec.encode({"BIGNUM": b, "NOTE": s})
        broker.produce("src", _frame(payload, 1), key=b"k")
    engine.pump()

    out_codec = AvroCodec(engine.sources["OUT"].record_schema())
    got = []
    for p in range(broker.topic("OUT").partitions):
        for m in broker.fetch("OUT", p, 0, 100):
            _, payload = unframe(m.value)
            rec = out_codec.decode(payload)
            got.append((rec["BIGNUM"], rec["NOTE"]))
    assert sorted(got) == sorted(vals), \
        "values corrupted by the native fast path"
