"""Byte-parity of the native SQL fast paths against the generic path.

The pipeline's three hot legs each have a native fast path (fused JSON→AVRO
CSAS, REKEY pass-through, vectorized COUNT CTAS); all of them promise
byte-identical topics and identical table state versus the per-row Python
path.  These tests run the full reference DDL twice — fast paths on and
forced off — and diff every output topic and the CTAS table.
"""

import json

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.stream.broker import Broker
from iotml.stream.native import NativeCodec, available
from iotml.streamproc.sql import SqlEngine, install_reference_pipeline

pytestmark = pytest.mark.skipif(not available(),
                                reason="native engine unavailable")


def _produce(broker, records, keys=None, topic="sensor-data"):
    broker.create_topic(topic, partitions=2)
    for i, rec in enumerate(records):
        key = (keys[i] if keys else f"car{i % 3}").encode()
        broker.produce(topic, json.dumps(rec).encode(), key=key,
                       timestamp_ms=i * 60_000)


def _fleet_records(n=40):
    gen = FleetGenerator(FleetScenario(num_cars=4))
    return [gen.row_record(gen.step_columns(), i % 4, KSQL_CAR_SCHEMA)
            for i in range(n)]


def _run_pipeline(records, disable_fast, keys=None):
    broker = Broker()
    _produce(broker, records, keys)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    fast_flags = []
    for q in engine.queries.values():
        t = q.task
        fast_flags.append((getattr(t, "_fused_json", None) is not None,
                           getattr(t, "_rekey_fast", False),
                           getattr(t, "_fast_count", False)))
        if disable_fast:
            if hasattr(t, "_fused_json"):
                t._fused_json = None
            if hasattr(t, "_rekey_fast"):
                t._rekey_fast = False
            if hasattr(t, "_fast_count"):
                t._fast_count = False
    engine.pump()
    topics = {}
    for topic in ("SENSOR_DATA_S_AVRO", "SENSOR_DATA_S_AVRO_REKEY",
                  "SENSOR_DATA_EVENTS_PER_5MIN_T"):
        spec = broker.topic(topic)
        topics[topic] = [
            (p, m.key, m.value)
            for p in range(spec.partitions)
            for m in broker.fetch(topic, p, 0, 100000)]
    table = engine.table("SENSOR_DATA_EVENTS_PER_5MIN_T")
    return topics, table, fast_flags


def test_fast_paths_engage_on_reference_ddl():
    _, _, flags = _run_pipeline(_fleet_records(8), disable_fast=False)
    assert any(f[0] for f in flags), "fused JSON CSAS did not engage"
    assert any(f[1] for f in flags), "REKEY pass-through did not engage"
    assert any(f[2] for f in flags), "COUNT fast path did not engage"


def test_reference_pipeline_byte_parity():
    records = _fleet_records(60)
    fast_topics, fast_table, _ = _run_pipeline(records, disable_fast=False)
    slow_topics, slow_table, _ = _run_pipeline(records, disable_fast=True)
    assert fast_topics == slow_topics
    assert fast_table == slow_table


def test_parity_with_hostile_rows():
    """Rows the native parsers must fall back on: producer-style key names
    (the KSQL null-column quirk), nulls, long strings, big ints, escapes,
    floats in int columns, malformed JSON."""
    base = _fleet_records(6)
    hostile = [
        # producer naming → mangled columns decode as NULL on both paths
        {"tire_pressure_1_1": 30, "coolant_temp": 90.0,
         "failure_occurred": "false"},
        {**base[0], "FAILURE_OCCURRED": "esc\"aped\nnewline"},
        {**base[1], "FAILURE_OCCURRED": "x" * 200},
        {**base[2], "TIRE_PRESSURE11": 2 ** 60},
        {**base[3], "TIRE_PRESSURE11": 1.5},
        {**base[4], "COOLANT_TEMP": None},
        {**base[5], "SPEED": 1e999},  # json.dumps → Infinity literal
    ]
    records = base + hostile
    fast_topics, fast_table, _ = _run_pipeline(records, disable_fast=False)
    slow_topics, slow_table, _ = _run_pipeline(records, disable_fast=True)
    assert fast_topics == slow_topics
    assert fast_table == slow_table


def test_parity_with_malformed_messages():
    """Non-JSON values and unframed Avro must drop identically."""
    broker_pairs = []
    for disable in (False, True):
        broker = Broker()
        broker.create_topic("sensor-data", partitions=1)
        recs = _fleet_records(4)
        for i, rec in enumerate(recs):
            broker.produce("sensor-data", json.dumps(rec).encode(),
                           key=b"car0", timestamp_ms=i)
        broker.produce("sensor-data", b"not json at all", key=b"car0",
                       timestamp_ms=9)
        broker.produce("sensor-data", b"[1,2,3]", key=b"car0",
                       timestamp_ms=10)
        engine = SqlEngine(broker)
        install_reference_pipeline(engine)
        if disable:
            for q in engine.queries.values():
                t = q.task
                if hasattr(t, "_fused_json"):
                    t._fused_json = None
                if hasattr(t, "_rekey_fast"):
                    t._rekey_fast = False
                if hasattr(t, "_fast_count"):
                    t._fast_count = False
        engine.pump()
        out = [(m.key, m.value)
               for m in broker.fetch("SENSOR_DATA_S_AVRO", 0, 0, 1000)]
        broker_pairs.append((out, engine.table(
            "SENSOR_DATA_EVENTS_PER_5MIN_T")))
    assert broker_pairs[0] == broker_pairs[1]


class TestNativeJsonDecode:
    def test_columnar_parity_with_json_loads(self):
        gen = FleetGenerator(FleetScenario(num_cars=3))
        recs = [gen.row_record(gen.step_columns(), i % 3, KSQL_CAR_SCHEMA)
                for i in range(32)]
        msgs = [json.dumps(r).encode() for r in recs]
        nc = NativeCodec(KSQL_CAR_SCHEMA)
        num, lab, nulls, fb = nc.json_decode_batch(msgs, stride=64)
        assert fb.sum() == 0
        assert nulls.sum() == 0
        numeric = [f.name for f in KSQL_CAR_SCHEMA.fields
                   if f.avro_type != "string"]
        strings = [f.name for f in KSQL_CAR_SCHEMA.fields
                   if f.avro_type == "string"]
        for i, r in enumerate(recs):
            d = {k.upper(): v for k, v in r.items()}
            assert [float(d[n]) for n in numeric] == num[i].tolist()
            assert [d[s].encode() for s in strings] == list(lab[i])

    def test_fallback_cases(self):
        nc = NativeCodec(KSQL_CAR_SCHEMA)
        gen = FleetGenerator(FleetScenario(num_cars=1))
        good = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
        cases = [
            b"not json",
            json.dumps({**good, "FAILURE_OCCURRED": "a\\u0041"}).encode(),
            json.dumps({**good, "TIRE_PRESSURE11": 2 ** 53}).encode(),
            json.dumps({**good, "TIRE_PRESSURE11": 0.5}).encode(),
            json.dumps({**good, "FAILURE_OCCURRED": 7}).encode(),
            json.dumps({**good, "extra": {"nested": 1}}).encode(),
            json.dumps(good).encode() + b" trailing",
        ]
        _, _, _, fb = nc.json_decode_batch(cases, stride=64)
        assert fb.tolist() == [1] * len(cases)
        # unknown scalar keys are fine (dict semantics: ignored by the star)
        ok_extra = json.dumps({**good, "extra": 1,
                               "other": "s"}).encode()
        _, _, _, fb = nc.json_decode_batch([ok_extra], stride=64)
        assert fb.tolist() == [0]
        # missing columns and explicit nulls are NULL rows, not fallbacks
        nullish = [b"{}",
                   json.dumps({**good, "COOLANT_TEMP": None}).encode()]
        _, _, nulls, fb = nc.json_decode_batch(nullish, stride=64)
        assert fb.tolist() == [0, 0]
        assert nulls[0].all()          # empty object: every column null
        cool = [f.name for f in KSQL_CAR_SCHEMA.fields].index("COOLANT_TEMP")
        assert nulls[1, cool] == 1 and nulls[1].sum() == 1

    def test_number_grammar_rejects_non_json_spellings(self):
        nc = NativeCodec(KSQL_CAR_SCHEMA)
        gen = FleetGenerator(FleetScenario(num_cars=1))
        good = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
        for bad_num in ("0x1A", "+1", "1.", ".5", "01", "1e", "- 1"):
            raw = json.dumps(good).encode().replace(
                json.dumps(good["COOLANT_TEMP"]).encode(),
                bad_num.encode(), 1)
            _, _, _, fb = nc.json_decode_batch([raw], stride=64)
            assert fb.tolist() == [1], bad_num

    def test_strictness_parity_ctrl_chars_and_utf8(self):
        """json.loads is strict: raw control chars in strings and invalid
        UTF-8 anywhere reject the whole message — the native parser must
        fall those rows back, and must ACCEPT valid multi-byte UTF-8."""
        nc = NativeCodec(KSQL_CAR_SCHEMA)
        gen = FleetGenerator(FleetScenario(num_cars=1))
        good = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
        raw = json.dumps(good).encode()
        reject = [
            raw.replace(b'"false"', b'"fa\x00se"'),   # NUL in value
            raw.replace(b'"SPEED"', b'"SP\x01ED"'),   # ctrl in key
            raw.replace(b'"false"', b'"fa\xffse"'),   # invalid utf-8
            raw.replace(b'"false"', b'"fa\xc0\xafse"'),  # overlong
        ]
        accept = [
            raw.replace(b'"false"', b'"fa\xc3\xa9se"'),        # 2-byte
            raw.replace(b'"false"', b'"fa\xf0\x9f\x98\x80se"'),  # 4-byte
        ]
        # an encoded UTF-16 surrogate is a fallback for the native parser
        # but NOT a Python reject (json.loads decodes bytes with
        # 'surrogatepass') — conservative fallback keeps parity, the
        # python leg owns whatever happens next
        surrogate = raw.replace(b'"false"', b'"fa\xed\xa0\x80se"')
        _, _, _, fb = nc.json_decode_batch(reject + [surrogate], stride=64)
        assert fb.tolist() == [1] * (len(reject) + 1)
        for m in reject:  # python oracle agrees these are rejects
            with pytest.raises((ValueError, UnicodeDecodeError)):
                json.loads(m)
        json.loads(surrogate)  # ...but accepts this one (surrogatepass)
        _, lab, _, fb = nc.json_decode_batch(accept, stride=64)
        assert fb.tolist() == [0, 0]
        assert lab[0, 0] == json.loads(accept[0])["FAILURE_OCCURRED"].encode()

    def test_duplicate_keys_last_wins(self):
        gen = FleetGenerator(FleetScenario(num_cars=1))
        good = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
        raw = json.dumps(good).encode()
        # append a duplicate of COOLANT_TEMP with a new value
        raw = raw[:-1] + b', "COOLANT_TEMP": 123.5}'
        nc = NativeCodec(KSQL_CAR_SCHEMA)
        num, _, _, fb = nc.json_decode_batch([raw], stride=64)
        assert fb.tolist() == [0]
        cool_idx = [f.name for f in KSQL_CAR_SCHEMA.fields
                    if f.avro_type != "string"].index("COOLANT_TEMP")
        assert num[0, cool_idx] == 123.5


def test_strict_decode_rejects_noncanonical_avro():
    """The pass-through paths may only forward bytes that decode→re-encode
    would reproduce exactly: trailing bytes, invalid UTF-8 in strings,
    non-minimal varints, and out-of-range union branches must all fall
    back (strict ValueError) even though lax decode accepts them."""
    from iotml.ops.avro import AvroCodec
    from iotml.ops.framing import frame

    codec = AvroCodec(KSQL_CAR_SCHEMA)
    nc = NativeCodec(KSQL_CAR_SCHEMA)
    gen = FleetGenerator(FleetScenario(num_cars=1))
    rec = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
    good = frame(codec.encode(rec), 7)
    # sanity: the clean message passes strict validation
    nc.decode_batch([good], strip=5, stride=64, strict=True)

    bad_cases = {
        "trailing": good + b"JUNK",
        # FAILURE_OCCURRED is the last field: a valid-length string whose
        # bytes are invalid UTF-8
        "utf8": good[:-5] + bytes([good[-5]]) + b"\xff\xff\xff\xff",
        # first field's union branch varint 1 (0x02) re-encoded overlong
        # as 0x82 0x00
        "overlong": good[:5] + b"\x82\x00" + good[6:],
    }
    for name, msg in bad_cases.items():
        with pytest.raises(ValueError):
            nc.decode_batch([msg], strip=5, stride=64, strict=True)
        # ...while the lax decode (the ingest path's tolerance) accepts
        # the trailing-bytes and overlong spellings
        if name != "utf8":
            nc.decode_batch([msg], strip=5, stride=64)


def test_rekey_passthrough_parity_with_trailing_junk():
    """End-to-end: a sensor-data JSON message is fine, but a crafted AVRO
    message with trailing junk lands in SENSOR_DATA_S_AVRO via direct
    produce; the REKEY output must be identical fast vs slow."""
    from iotml.ops.avro import AvroCodec
    from iotml.ops.framing import frame

    outs = []
    for disable in (False, True):
        broker = Broker()
        _produce(broker, _fleet_records(6))
        engine = SqlEngine(broker)
        install_reference_pipeline(engine)
        codec = AvroCodec(KSQL_CAR_SCHEMA)
        gen = FleetGenerator(FleetScenario(num_cars=1))
        rec = gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)
        broker.produce("SENSOR_DATA_S_AVRO",
                       frame(codec.encode(rec), 3) + b"TRAILING",
                       key=b"carX", timestamp_ms=5)
        if disable:
            for q in engine.queries.values():
                t = q.task
                if hasattr(t, "_fused_json"):
                    t._fused_json = None
                if hasattr(t, "_rekey_fast"):
                    t._rekey_fast = False
                if hasattr(t, "_fast_count"):
                    t._fast_count = False
        engine.pump()
        spec = broker.topic("SENSOR_DATA_S_AVRO_REKEY")
        outs.append([(p, m.key, m.value) for p in range(spec.partitions)
                     for m in broker.fetch("SENSOR_DATA_S_AVRO_REKEY",
                                           p, 0, 10000)])
    assert outs[0] == outs[1]


def test_trusted_passthrough_byte_parity_and_scope():
    """trusted_passthrough=True skips rekey re-validation only for
    engine-produced sources, with byte-identical output on clean data;
    sources fed by external producers keep validating regardless."""
    outs = []
    for trusted in (False, True):
        broker = Broker()
        _produce(broker, _fleet_records(40))
        engine = SqlEngine(broker, trusted_passthrough=trusted)
        install_reference_pipeline(engine)
        rekey = next(q.task for q in engine.queries.values()
                     if getattr(q.task, "_rekey_fast", False))
        # scope: the REKEY source is the engine's own AVRO leg → trusted
        # follows the engine flag; its upstream (external sensor-data)
        # is never trusted
        assert rekey._trusted is trusted
        engine.pump()
        spec = broker.topic("SENSOR_DATA_S_AVRO_REKEY")
        outs.append([(p, m.key, m.value) for p in range(spec.partitions)
                     for m in broker.fetch("SENSOR_DATA_S_AVRO_REKEY",
                                           p, 0, 10000)])
    assert outs[0] == outs[1] and len(outs[0]) == 40


def test_json_decode_float32_range_guard():
    """A finite JSON number beyond float32 range in an Avro 'float' column
    must fall back: the Python leg raises on encode (struct.pack '<f'
    overflow) and owns that error semantics.  Double columns keep the
    full float64 range."""
    from iotml.core.schema import Field, RecordSchema

    schema = RecordSchema(
        name="F32Rec", namespace="t",
        fields=(Field("a", "float"), Field("b", "double")))
    nc = NativeCodec(schema)
    cases = [
        (b'{"A": 1.5, "B": 1.5}', 0),        # in range
        (b'{"A": 3.3e38, "B": 1.0}', 0),     # near float32 max, ok
        (b'{"A": 3.5e38, "B": 1.0}', 1),     # finite overflow -> python
        (b'{"A": 1e999, "B": 1.0}', 1),      # strtod infinity -> python
        (b'{"A": 1.0, "B": 1e300}', 0),      # double keeps its range
    ]
    _, _, _, fb = nc.json_decode_batch([c for c, _ in cases], stride=64)
    assert fb.tolist() == [want for _, want in cases]
