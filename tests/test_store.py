"""iotml.store — segmented log, crash recovery, offsets, replay, and
the broker/wire/consumer integration of the durable backend.

Recovery edge cases follow the ISSUE-5 checklist: torn tail record,
empty tail segment (death right after a roll), index/log mismatch
rebuilt from the log, and byte-identical replay after recovery (seeded
via the chaos schedule machinery, so the corruption pattern replays)."""

import os
import random
import struct

import pytest

from iotml.store import (OffsetsFile, SegmentedLog, SegmentWriter,
                         StorePolicy, crc32c)
from iotml.store import segment as seg
from iotml.store.segment import _crc32c_py
from iotml.stream.broker import Broker, OffsetOutOfRangeError


def _fill(log, n, ts0=1000, payload=b"v"):
    for i in range(n):
        log.append(f"k{i}".encode() if i % 3 else None,
                   payload + str(i).encode(), ts0 + i)


def _dump(log):
    return log.read_from(log.base_offset, 10 ** 6)


# ------------------------------------------------------------- framing
def test_crc32c_known_answer_and_fast_path_parity():
    # the canonical CRC32C check value ("123456789" -> 0xE3069283)
    assert _crc32c_py(b"123456789") == 0xE3069283
    rng = random.Random(7)
    for _ in range(64):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(200)))
        assert crc32c(blob) == _crc32c_py(blob)


def test_record_roundtrip_with_and_without_headers():
    hdrs = (("iotml_trace", b"wire-bytes"), ("other", "strval"))
    frame = seg.encode_record(42, b"key", b"value", 1234, hdrs)
    rows = list(seg.scan_records(frame))
    assert len(rows) == 1
    _pos, end, off, key, value, ts, got = rows[0]
    assert (off, key, value, ts) == (42, b"key", b"value", 1234)
    assert got == (("iotml_trace", b"wire-bytes"), ("other", b"strval"))
    assert end == len(frame)
    # null key, no headers
    frame2 = seg.encode_record(0, None, b"v", 0, None)
    (_p, _e, off, key, value, ts, hdrs2), = seg.scan_records(frame2)
    assert key is None and hdrs2 is None


def test_scan_stops_at_corrupt_frame():
    a = seg.encode_record(0, None, b"a", 1, None)
    b = seg.encode_record(1, None, b"b", 2, None)
    flipped = bytearray(a + b)
    flipped[-1] ^= 0xFF  # corrupt b's payload: its CRC must fail
    rows = list(seg.scan_records(bytes(flipped)))
    assert [r[2] for r in rows] == [0]


def test_segment_writer_rejects_bad_fsync_policy(tmp_path):
    with pytest.raises(ValueError, match="never|interval|always"):
        SegmentWriter(str(tmp_path / "x.log"), fsync="sometimes")
    with pytest.raises(ValueError):
        StorePolicy(fsync="bogus")


# ------------------------------------------------------ log + recovery
def test_roll_retention_and_sparse_index(tmp_path):
    pol = StorePolicy(fsync="never", segment_bytes=300,
                      index_interval_bytes=128)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 60)
    assert len(log._segments) > 3          # rolled by bytes
    assert log.end_offset == 60
    # the sparse index is sparse: far fewer entries than records
    assert 0 < len(log.index_entries()) < 20
    # reads seek through segments and honor max_records
    chunk = log.read_from(17, 5)
    assert [r[0] for r in chunk] == [17, 18, 19, 20, 21]
    # retention by bytes drops whole sealed head segments
    log.policy.retention_bytes = 600
    dropped = log.enforce_retention()
    assert dropped > 0 and log.base_offset == dropped
    with pytest.raises(LookupError):
        log.read_from(0)
    log.close()


def test_retention_by_age_against_newest_timestamp(tmp_path):
    pol = StorePolicy(fsync="never", segment_bytes=200, retention_ms=50)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 30, ts0=1000)   # ts 1000..1029
    assert log.enforce_retention() == 0  # all within 50ms of newest
    log.append(None, b"new", 5000)
    dropped = log.enforce_retention()
    assert dropped > 0
    # the active segment (holding ts=5000) always survives
    assert any(r[3] == 5000 for r in _dump(log))
    log.close()


def test_recovery_truncates_torn_tail_and_replays_byte_identically(tmp_path):
    """Seeded via the chaos schedule machinery: the scenario's RNG picks
    the torn-blob shape, so the corruption pattern itself replays."""
    from iotml.chaos.scenarios import build

    sched = build("broker-crash-recover", seed=13, records=100)
    rng = random.Random(sched.seed)
    pol = StorePolicy(fsync="always", segment_bytes=400)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 40)
    before = _dump(log)
    torn = bytes(rng.randrange(256) for _ in range(rng.randrange(8, 64)))
    n = log.simulate_torn_write(struct.pack(">I", 1 << 30) + torn)
    # no close(): the process "dies" here
    log2 = SegmentedLog(str(tmp_path), pol)
    assert log2.recovered_truncated_bytes == n
    assert _dump(log2) == before            # byte-identical replay
    assert log2.append(None, b"after", 9999) == 40  # appends continue
    log2.close()
    # a second mount is clean: recovery is idempotent
    log3 = SegmentedLog(str(tmp_path), pol)
    assert log3.recovered_truncated_bytes == 0
    assert [r[0] for r in _dump(log3)] == list(range(41))
    log3.close()


def test_recovery_drops_empty_tail_segment(tmp_path):
    """Death right after a roll leaves a zero-record tail segment; the
    mount must drop it and resume appending at the right offset."""
    pol = StorePolicy(fsync="never", segment_bytes=10 ** 9)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 10)
    log.roll()  # seals segment 0, creates an empty active segment
    log.close()
    empties = [n for n in os.listdir(str(tmp_path)) if n.endswith(".log")
               and os.path.getsize(tmp_path / n) == 0]
    assert empties  # the crash artifact exists
    log2 = SegmentedLog(str(tmp_path), pol)
    assert log2.end_offset == 10
    assert log2.recovered_truncated_bytes == 0  # empty tail is not "torn"
    assert log2.append(None, b"next", 0) == 10
    assert [r[0] for r in _dump(log2)] == list(range(11))
    log2.close()


def _flip_last_byte(path, size):
    with open(path, "r+b") as fh:
        fh.seek(size - 1)
        b = fh.read(1)
        fh.seek(size - 1)
        fh.write(bytes([b[0] ^ 0xFF]))


def test_corrupt_sealed_segment_gap_is_jumped_not_stalled(tmp_path):
    """A corrupted frame inside a SEALED (non-tail) segment must never
    stall readers (a stalled at_end() would hang the scorer) — on BOTH
    mount paths: the rescan path (no sidecars) truncates and counts the
    corruption; the trusted-sidecar fast path discovers it at read time
    and skips the hole.  Either way every intact later record serves."""
    pol = StorePolicy(fsync="never", segment_bytes=200)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 40)
    assert len(log._segments) > 2
    victim = log._segments[1]  # sealed, mid-log
    log.close()
    _flip_last_byte(victim.path, victim.size)

    def drain(log):
        """Cursor-style reads, like a consumer: a hole may only ever
        appear BETWEEN batches (batch starts), never inside one — the
        replica's realignment check reads msgs[0].offset only."""
        got, off = [], 0
        while True:
            chunk = log.read_from(off, 1000)
            if not chunk:
                return got
            offs = [r[0] for r in chunk]
            assert offs == list(range(offs[0], offs[0] + len(offs)))
            got += offs
            off = offs[-1] + 1

    # path 1 — trusted sidecars (size stamp still matches): mount stays
    # O(tail), the corruption surfaces at read time as a skipped hole
    log2 = SegmentedLog(str(tmp_path), pol)
    assert log2.recovered_truncated_bytes == 0
    got = drain(log2)
    assert got[0] == 0 and got[-1] == 39          # later segments served
    hole = set(range(40)) - set(got)
    assert hole and all(victim.base_offset <= o < 40 for o in hole)
    log2.close()

    # path 2 — sidecars gone: full rescan detects, truncates, counts
    for n in list(os.listdir(str(tmp_path))):
        if n.endswith((".index", ".timeindex")):
            os.remove(str(tmp_path / n))
    log3 = SegmentedLog(str(tmp_path), pol)
    assert log3.recovered_truncated_bytes > 0
    got = drain(log3)
    assert got[0] == 0 and got[-1] == 39
    hole = set(range(40)) - set(got)
    assert hole and all(victim.base_offset <= o < 40 for o in hole)
    # a reader starting INSIDE the hole also gets un-stalled
    assert log3.read_from(min(hole), 10)[0][0] == max(hole) + 1
    log3.close()


def test_index_log_mismatch_rebuilt_from_log(tmp_path):
    """Sidecar indexes are an accelerator, never ground truth: a
    corrupted or deleted .index/.timeindex must not change reads."""
    pol = StorePolicy(fsync="never", segment_bytes=300)
    log = SegmentedLog(str(tmp_path), pol)
    _fill(log, 50)
    before = _dump(log)
    ts_probe = log.offset_for_timestamp(1025)
    log.close()
    sidecars = [n for n in os.listdir(str(tmp_path))
                if n.endswith((".index", ".timeindex"))]
    assert sidecars  # sealed segments published them
    for i, name in enumerate(sidecars):
        p = str(tmp_path / name)
        if i % 2:
            os.remove(p)
        else:  # garbage content: disagrees with the log
            with open(p, "wb") as fh:
                fh.write(b"\xff" * 24)
    log2 = SegmentedLog(str(tmp_path), pol)
    assert _dump(log2) == before
    assert log2.offset_for_timestamp(1025) == ts_probe == 25
    log2.close()


def test_timestamp_index_and_read_since(tmp_path):
    log = SegmentedLog(str(tmp_path), StorePolicy(fsync="never",
                                                  segment_bytes=250))
    _fill(log, 40, ts0=100)
    assert log.offset_for_timestamp(0) == 0
    assert log.offset_for_timestamp(120) == 20
    assert log.offset_for_timestamp(10 ** 9) == log.end_offset
    assert [r[0] for r in log.read_since(135, 10)] == [35, 36, 37, 38, 39]
    # non-monotone timestamps: earliest offset at/after T, Kafka's rule
    log.append(None, b"late", 50)   # older ts after newer ones
    assert log.offset_for_timestamp(120) == 20
    log.close()


def test_align_base_and_reset(tmp_path):
    log = SegmentedLog(str(tmp_path), StorePolicy(fsync="never"))
    log.align_base(500)
    assert log.base_offset == log.end_offset == 500
    assert log.append(None, b"v", 0) == 500
    with pytest.raises(ValueError):
        log.align_base(900)
    log.reset(42)
    assert log.base_offset == log.end_offset == 42
    assert log.append(None, b"w", 0) == 42
    log.close()


# -------------------------------------------------------------- offsets
def test_offsets_file_compacts_and_survives_torn_tail(tmp_path):
    of = OffsetsFile(str(tmp_path), fsync="always", compact_ratio=4)
    for i in range(100):
        of.commit("g", "t", i % 3, i)
    size_after_compaction = os.path.getsize(of.path)
    # 100 appended records over 3 live keys MUST have compacted
    assert of._records < 100
    assert size_after_compaction < 100 * 40
    of.commit_many("g2", "t", [(0, 7), (1, 9)])
    of.close()
    of2 = OffsetsFile(str(tmp_path))
    assert of2.get("g", "t", 0) == 99
    assert of2.get("g2", "t", 1) == 9
    # torn tail: the partial record is dropped, the rest loads
    of2.close()
    with open(of2.path, "ab") as fh:
        fh.write(b"\x00\x00\x10\x00partial")
    of3 = OffsetsFile(str(tmp_path))
    assert of3.recovered_truncated_bytes > 0
    assert of3.get("g", "t", 0) == 99
    of3.close()


# ----------------------------------------------------- broker (durable)
def test_durable_broker_restart_resumes_everything(tmp_path):
    d = str(tmp_path / "store")
    pol = dict(fsync="always", segment_bytes=500)
    b = Broker(store_dir=d, store_policy=StorePolicy(**pol))
    b.create_topic("t", partitions=2, retention_bytes=0)
    for i in range(30):
        b.produce("t", f"v{i}".encode(), key=f"k{i % 4}".encode(),
                  timestamp_ms=i)
    b.produce_many("t", [(None, b"bulk", 99), (b"k", b"bulk2", 100)])
    b.commit("g", "t", 0, 5)
    b.commit_many("g", "t", [(0, 7), (1, 3)])
    ends = [b.end_offset("t", p) for p in (0, 1)]
    rows = [b.fetch("t", p, b.begin_offset("t", p), 1000) for p in (0, 1)]
    b.close()

    b2 = Broker(store_dir=d, store_policy=StorePolicy(**pol))
    assert b2.durable and b2.topic("t").partitions == 2
    assert [b2.end_offset("t", p) for p in (0, 1)] == ends
    assert [b2.fetch("t", p, b2.begin_offset("t", p), 1000)
            for p in (0, 1)] == rows
    assert b2.committed("g", "t", 0) == 7
    assert b2.committed("g", "t", 1) == 3
    b2.close()


def test_durable_broker_replay_api_and_metric(tmp_path):
    from iotml.store.log import store_replay_records

    b = Broker(store_dir=str(tmp_path / "s"))
    b.create_topic("t")
    for i in range(20):
        b.produce("t", str(i).encode(), partition=0, timestamp_ms=1000 + i)
    before = store_replay_records.value()
    msgs = b.read_since("t", 0, 1015, 100)
    assert [m.offset for m in msgs] == [15, 16, 17, 18, 19]
    assert b.offset_for_timestamp("t", 0, 1015) == 15
    assert store_replay_records.value() == before + 5
    b.close()


def test_durable_retention_segment_granular(tmp_path):
    b = Broker(store_dir=str(tmp_path / "s"),
               store_policy=StorePolicy(fsync="never", segment_bytes=300))
    b.create_topic("t", retention_bytes=700)
    for i in range(100):
        b.produce("t", b"x" * 20, partition=0)
    assert b.begin_offset("t", 0) > 0        # head segments deleted
    assert b.end_offset("t", 0) == 100
    with pytest.raises(OffsetOutOfRangeError):
        b.fetch("t", 0, 0)
    # count retention too (the CLI's --retention on a durable platform):
    # segment-granular, may over-retain up to one segment, never under
    b.create_topic("tc", retention_messages=10)
    for i in range(100):
        b.produce("tc", b"y" * 20, partition=0)
    retained = b.end_offset("tc", 0) - b.begin_offset("tc", 0)
    assert 10 <= retained < 40
    b.close()


def test_durable_topic_retention_inherit_vs_explicit_unlimited(tmp_path):
    """None (unset) inherits the store-wide retention default; 0 (the
    wire's -1 sentinel) explicitly opts the topic out of it."""
    b = Broker(store_dir=str(tmp_path / "s"),
               store_policy=StorePolicy(fsync="never", segment_bytes=300,
                                        retention_bytes=700))
    b.create_topic("inherits")           # None: store default applies
    b.create_topic("unlimited", retention_bytes=0)  # explicit opt-out
    for i in range(100):
        b.produce("inherits", b"x" * 20, partition=0)
        b.produce("unlimited", b"x" * 20, partition=0)
    assert b.begin_offset("inherits", 0) > 0
    assert b.begin_offset("unlimited", 0) == 0
    assert b.end_offset("unlimited", 0) == 100
    b.close()


def test_store_metrics_registered_and_live(tmp_path):
    from iotml.obs import metrics as obs_metrics

    b = Broker(store_dir=str(tmp_path / "s"))
    b.create_topic("t")
    b.produce("t", b"v", partition=0)
    rendered = obs_metrics.default_registry.render()
    for family in ("iotml_store_segment_bytes", "iotml_store_fsync_seconds",
                   "iotml_store_recovery_truncated_bytes",
                   "iotml_store_replay_records_total"):
        assert family in rendered, family
    from iotml.store.log import store_segment_bytes

    assert store_segment_bytes.value(topic="t", partition="0") > 0
    b.close()


# --------------------------------------------------------- wire + store
def test_wire_out_of_range_and_timestamp_listing(tmp_path):
    """The trimmed-log read path over TCP: error 1 + earliest offset in
    the response, client raises OffsetOutOfRangeError, StreamConsumer
    auto-resets; ListOffsets with ts>=0 answers the replay cursor."""
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    b = Broker()
    b.create_topic("t", retention_messages=5)
    for i in range(20):
        b.produce("t", str(i).encode(), partition=0, timestamp_ms=i)
    with KafkaWireServer(b) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        with pytest.raises(OffsetOutOfRangeError) as ei:
            client.fetch("t", 0, 0)
        assert ei.value.earliest == 15
        assert client.offset_for_timestamp("t", 0, 17) == 17
        # consumer over the wire: documented auto-reset-to-earliest
        c = StreamConsumer(client, ["t:0:0"], group="g", eof=False)
        assert [m.offset for m in c.poll()] == [15, 16, 17, 18, 19]
        client.close()


def test_wire_create_topic_carries_retention_configs(tmp_path):
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    b = Broker()
    with KafkaWireServer(b) as srv:
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        client.create_topic("t", partitions=2, retention_messages=9,
                            retention_bytes=1234, retention_ms=5678)
        spec = b.topic("t")
        assert (spec.retention_messages, spec.retention_bytes,
                spec.retention_ms) == (9, 1234, 5678)
        with pytest.raises(ValueError):
            client.create_topic("neg", retention_ms=-4)
        # Kafka's documented -1 'unlimited' sentinel maps to EXPLICIT
        # unlimited (0) — distinct from None/unset, which on a durable
        # broker would inherit the store-wide retention default
        client.create_topic("unlim", retention_ms=-1)
        assert b.topic("unlim").retention_ms == 0
        client.close()


# ---------------------------------------------------- trainer backfill
def test_trainer_backfills_from_timestamp_on_cold_start(tmp_path):
    """ContinuousTrainer with backfill_since_ms: a first incarnation (no
    committed cursor) starts at the replay offset; a partition WITH a
    commit resumes from it untouched."""
    from iotml.train.artifacts import ArtifactStore
    from iotml.train.live import ContinuousTrainer

    b = Broker(store_dir=str(tmp_path / "s"))
    b.create_topic("t", partitions=2)
    for i in range(50):
        b.produce("t", str(i).encode(), partition=i % 2,
                  timestamp_ms=1000 + i)
    b.commit("cold", "t", 1, 11)  # partition 1 has a committed cursor
    ct = ContinuousTrainer(b, "t", ArtifactStore(str(tmp_path / "art")),
                           group="cold", backfill_since_ms=1030)
    pos = dict((p, off) for _t, p, off in ct.consumer.positions())
    assert pos[0] == b.offset_for_timestamp("t", 0, 1030)
    assert pos[0] > 0
    assert pos[1] == 11  # resume beats replay
    b.close()


def test_consumer_seek_to_timestamp(tmp_path):
    from iotml.stream.consumer import StreamConsumer

    b = Broker(store_dir=str(tmp_path / "s"))
    b.create_topic("t")
    for i in range(10):
        b.produce("t", str(i).encode(), partition=0, timestamp_ms=100 + i)
    c = StreamConsumer(b, ["t:0:0"], group="g")
    c.seek_to_timestamp(106)
    assert [m.offset for m in c.poll()] == [6, 7, 8, 9]
    b.close()


def test_sanitized_topic_names_never_share_a_directory(tmp_path):
    """"a b" and "a_b" sanitize identically; two SegmentedLogs over one
    directory would interleave frames — the dir names must diverge."""
    from iotml.store.mount import _dirname_for

    assert _dirname_for("a b") != _dirname_for("a_b")
    assert _dirname_for("plain-topic.ok") == "plain-topic.ok"
    b = Broker(store_dir=str(tmp_path / "s"))
    b.create_topic("a b")
    b.create_topic("a_b")
    b.produce("a b", b"spaced", partition=0)
    b.produce("a_b", b"underscored", partition=0)
    assert b.fetch("a b", 0, 0)[0].value == b"spaced"
    assert b.fetch("a_b", 0, 0)[0].value == b"underscored"
    assert b.end_offset("a b", 0) == b.end_offset("a_b", 0) == 1
    b.close()


def test_store_dir_single_writer_lock(tmp_path):
    """Two broker PROCESSES must not share one store dir (interleaved
    frames in the active segment are unrecoverable corruption); a
    remount in the SAME process (the crash-simulation path) must work."""
    import subprocess
    import sys

    d = str(tmp_path / "s")
    b = Broker(store_dir=d)
    b.create_topic("t")
    # same-process remount (chaos runner's kill path): allowed
    b2 = Broker(store_dir=d)
    assert "t" in b2.topics()
    # a second PROCESS: refused while this one holds the mount
    probe = subprocess.run(
        [sys.executable, "-c",
         "from iotml.stream.broker import Broker\n"
         f"Broker(store_dir={d!r})"],
        capture_output=True, text=True, cwd="/root/repo")
    assert probe.returncode != 0
    assert "locked by another broker process" in probe.stderr
    b.close()
    b2.close()
    # lock released with the mount: the next process may take it
    probe2 = subprocess.run(
        [sys.executable, "-c",
         "from iotml.stream.broker import Broker\n"
         f"br = Broker(store_dir={d!r}); br.close()"],
        capture_output=True, text=True, cwd="/root/repo")
    assert probe2.returncode == 0, probe2.stderr


# -------------------------------------------------- platform / config
def test_platform_durable_mode_survives_restart(tmp_path):
    """--durable end to end: a Platform over a store dir, records in,
    torn down; a SECOND Platform over the same dir serves the same
    records and committed offsets (the quickstart's restart story)."""
    from iotml.cli.up import Platform

    d = str(tmp_path / "plat")
    plat = Platform(partitions=2, store_dir=d,
                    store_policy=StorePolicy(fsync="always")).start()
    try:
        plat.broker.create_topic("raw")  # outside the reference topic set
        for i in range(10):
            plat.broker.produce("raw", str(i).encode(), partition=0)
        plat.broker.commit("g", "raw", 0, 4)
    finally:
        plat.stop()

    plat2 = Platform(partitions=2, store_dir=d,
                     store_policy=StorePolicy(fsync="always")).start()
    try:
        assert plat2.endpoints().get("store") == d
        assert plat2.broker.end_offset("raw", 0) == 10
        assert plat2.broker.committed("g", "raw", 0) == 4
        assert "sensor-data" in plat2.broker.topics()
    finally:
        plat2.stop()


def test_store_config_section_resolves_from_env():
    from iotml.config import load_config
    from iotml.store import StorePolicy as SP

    cfg, _ = load_config([], env={"IOTML_STORE_DIR": "/tmp/x",
                                  "IOTML_STORE_FSYNC": "always",
                                  "IOTML_STORE_RETENTION_MS": "100000"})
    assert cfg.store.dir == "/tmp/x"
    assert cfg.store.fsync == "always"
    assert cfg.store.retention_ms == 100000
    pol = SP.from_config(cfg.store)
    assert pol.fsync == "always" and pol.retention_ms == 100000
    with pytest.raises(ValueError):
        load_config([], env={"IOTML_STORE_FSYNCK": "always"})