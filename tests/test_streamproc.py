"""KSQL-equivalent stream transforms: convert → rekey → tumbling counts,
then the converted topic must feed the ML pipeline unchanged (the reference
topology: sensor-data → SENSOR_DATA_S_AVRO → TF consumer)."""

import json

import numpy as np

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.ops.avro import AvroCodec
from iotml.ops.framing import strip_frame
from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.streamproc.tasks import JsonToAvro, RekeyByCar, TumblingCounter


def seed_json_stream(num_cars=20, ticks=6, interval_s=100.0):
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=num_cars, failure_rate=0.1,
                                       interval_s=interval_s))
    n = gen.publish(broker, "sensor-data", n_ticks=ticks, encoding="json")
    return broker, n


def test_json_to_avro_convert():
    broker, n = seed_json_stream()
    task = JsonToAvro(broker)
    assert task.process_available() == n
    msgs = broker.fetch("SENSOR_DATA_S_AVRO", 0, 0, 10)
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rec = codec.decode(strip_frame(msgs[0].value))
    assert rec["FAILURE_OCCURRED"] in ("true", "false")
    assert isinstance(rec["SPEED"], float)
    assert isinstance(rec["TIRE_PRESSURE11"], int)
    # source JSON and converted Avro agree value-for-value
    src = json.loads(broker.fetch("sensor-data", 0, 0, 1)[0].value)
    assert rec["SPEED"] == float(src["speed"])

    # incremental: nothing new → nothing emitted; new data → only the delta
    assert task.process_available() == 0


def test_convert_is_incremental():
    broker, n = seed_json_stream(num_cars=5, ticks=2)
    task = JsonToAvro(broker)
    task.process_available()
    gen2 = FleetGenerator(FleetScenario(num_cars=5, seed=99))
    gen2.publish(broker, "sensor-data", n_ticks=1, encoding="json")
    assert task.process_available() == 5


def test_rekey_by_car_gives_per_car_partitions():
    broker, n = seed_json_stream(num_cars=8, ticks=4)
    JsonToAvro(broker).process_available()
    rekey = RekeyByCar(broker, "SENSOR_DATA_S_AVRO", "SENSOR_DATA_S_AVRO_REKEY",
                       partitions=4)
    assert rekey.process_available() == n
    # every car's records live in exactly one partition, in order
    per_part = {}
    for p in range(4):
        for m in broker.fetch("SENSOR_DATA_S_AVRO_REKEY", p, 0, 10_000):
            per_part.setdefault(m.key, set()).add(p)
    assert len(per_part) == 8
    assert all(len(parts) == 1 for parts in per_part.values())


def test_tumbling_counter_5min_windows():
    # interval 100s → 3 ticks per 5-min window
    broker, _ = seed_json_stream(num_cars=4, ticks=6, interval_s=100.0)
    JsonToAvro(broker).process_available()
    rekey = RekeyByCar(broker, "SENSOR_DATA_S_AVRO", "SENSOR_DATA_S_AVRO_REKEY",
                       partitions=2)
    rekey.process_available()
    counter = TumblingCounter(broker)
    counter.process_available()
    table = counter.table()
    # 6 ticks at 100s: ts = 100..600s → windows 0 and 300 get 2/3 + rest
    assert sum(table.values()) == 24
    cars = {car for car, _ in table}
    assert len(cars) == 4
    for (car, win), count in table.items():
        assert win % (5 * 60 * 1000) == 0
    # emitted updates are JSON rows keyed by car
    msgs = broker.fetch("SENSOR_DATA_EVENTS_PER_5MIN_T", 0, 0, 100)
    row = json.loads(msgs[0].value)
    assert set(row) == {"CAR", "WINDOW_START_MS", "EVENT_COUNT"}


def test_task_restart_resumes_from_commit():
    """A rebuilt task (same group) must not re-emit processed records."""
    broker, n = seed_json_stream(num_cars=6, ticks=3)
    JsonToAvro(broker, group="conv").process_available()
    assert broker.end_offset("SENSOR_DATA_S_AVRO", 0) == n
    # "restart": new task instance, same broker + group
    JsonToAvro(broker, group="conv").process_available()
    assert broker.end_offset("SENSOR_DATA_S_AVRO", 0) == n  # no duplicates


def test_full_ksql_chain_feeds_training_pipeline():
    broker, n = seed_json_stream(num_cars=30, ticks=10)
    JsonToAvro(broker).process_available()
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    batches = list(SensorBatches(consumer, batch_size=50))
    total = sum(b.n_valid for b in batches)
    assert total == n
    x = np.concatenate([b.x[: b.n_valid] for b in batches])
    assert np.isfinite(x).all()
    # healthy sensors normalize into (-1,1); failure-mode records may exceed
    # it (that's the anomaly signal), so just bound loosely
    assert np.all(np.abs(x) <= 10.0)
