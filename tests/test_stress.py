"""Concurrency stress: the platform's shared-state paths under real thread
contention.  The reference has no race detection anywhere (SURVEY §5); this
is the de-facto sanitizer for the rebuild's hot shared structures — the
broker log, the wire server, and the group coordinator under churn."""

import threading
import time

import pytest

from iotml.stream.broker import Broker
from iotml.stream.group import GroupConsumer, GroupCoordinator
from iotml.stream.kafka_wire import (KafkaWireBroker, KafkaWireServer,
                                     RemoteGroupCoordinator)

N_PRODUCERS = 4
N_PER_PRODUCER = 500


def test_concurrent_producers_one_broker_no_loss():
    broker = Broker()
    broker.create_topic("t", partitions=8)

    def produce(wid):
        for i in range(N_PER_PRODUCER):
            broker.produce("t", f"{wid}:{i}".encode(), key=f"{wid}".encode())

    threads = [threading.Thread(target=produce, args=(w,))
               for w in range(N_PRODUCERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    got = set()
    for p in range(8):
        off = 0
        while True:
            msgs = broker.fetch("t", p, off, 4096)
            if not msgs:
                break
            got.update(m.value for m in msgs)
            off = msgs[-1].offset + 1
    assert len(got) == N_PRODUCERS * N_PER_PRODUCER


def test_wire_server_concurrent_clients_no_loss():
    """Many TCP clients producing + consuming + committing at once; every
    record lands exactly once in the log, none vanish under contention."""
    broker = Broker()
    broker.create_topic("t", partitions=4)
    errors = []

    with KafkaWireServer(broker) as srv:
        addr = f"127.0.0.1:{srv.port}"

        def produce(wid):
            try:
                client = KafkaWireBroker(addr)
                for i in range(200):
                    client.produce("t", f"{wid}:{i}".encode(),
                                   key=f"{wid}".encode())
                client.close()
            except Exception as e:  # pragma: no cover - failure reporting
                errors.append(e)

        def consume(wid):
            try:
                client = KafkaWireBroker(addr)
                for p in range(4):
                    client.fetch("t", p, 0)
                    client.commit(f"g{wid}", "t", p, 1)
                client.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=produce, args=(w,))
                   for w in range(4)]
        threads += [threading.Thread(target=consume, args=(w,))
                    for w in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    assert not errors
    total = sum(broker.end_offset("t", p) for p in range(4))
    assert total == 4 * 200


def test_group_churn_under_concurrent_polling():
    """Members joining/leaving while others poll: no exceptions, no lost
    records, group converges to the survivors."""
    broker = Broker()
    broker.create_topic("t", partitions=8)
    for i in range(2000):
        broker.produce("t", f"r{i}".encode(), partition=i % 8)

    coord = GroupCoordinator(broker, "g", session_timeout_s=30.0)
    seen = set()
    seen_lock = threading.Lock()
    stop = threading.Event()
    errors = []

    def steady(wid):
        try:
            c = GroupConsumer(coord, ["t"])
            while not stop.is_set():
                msgs = c.poll(100)
                with seen_lock:
                    seen.update(m.value for m in msgs)
                c.commit()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def churner():
        try:
            for _ in range(10):
                c = GroupConsumer(coord, ["t"])
                msgs = c.poll(10)
                with seen_lock:
                    # close() commits, so these reads count as consumed
                    seen.update(m.value for m in msgs)
                c.close()  # commit + leave → rebalance storm
        except Exception as e:  # pragma: no cover
            errors.append(e)

    workers = [threading.Thread(target=steady, args=(w,)) for w in range(2)]
    churn = threading.Thread(target=churner)
    for t in workers:
        t.start()
    churn.start()
    churn.join()
    # drain: give the steady members time to finish everything
    import time
    deadline = time.time() + 20
    while time.time() < deadline:
        with seen_lock:
            if len(seen) == 2000:
                break
        time.sleep(0.1)
    stop.set()
    for t in workers:
        t.join(timeout=10)

    assert not errors
    assert len(seen) == 2000  # churn may redeliver, but never loses


def test_remote_group_churn_over_wire():
    """The same churn through real TCP + the wire-protocol coordinator."""
    broker = Broker()
    broker.create_topic("t", partitions=6)
    for i in range(600):
        broker.produce("t", f"r{i}".encode(), partition=i % 6)

    errors = []
    seen = set()
    lock = threading.Lock()

    with KafkaWireServer(broker) as srv:
        addr = f"127.0.0.1:{srv.port}"
        stop = threading.Event()

        def steady():
            try:
                client = KafkaWireBroker(addr)
                c = GroupConsumer(RemoteGroupCoordinator(client, "g"), ["t"])
                while not stop.is_set():
                    msgs = c.poll(100)
                    with lock:
                        seen.update(m.value for m in msgs)
                    c.commit()
                c.close()
                client.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def churner():
            try:
                for _ in range(5):
                    client = KafkaWireBroker(addr)
                    c = GroupConsumer(RemoteGroupCoordinator(client, "g"),
                                      ["t"])
                    msgs = c.poll(10)
                    with lock:
                        # close() commits, so these reads count as consumed
                        seen.update(m.value for m in msgs)
                    c.close()
                    client.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        s = threading.Thread(target=steady)
        ch = threading.Thread(target=churner)
        s.start()
        ch.start()
        ch.join()
        import time
        deadline = time.time() + 20
        while time.time() < deadline:
            with lock:
                if len(seen) == 600:
                    break
            time.sleep(0.1)
        stop.set()
        s.join(timeout=10)

    assert not errors
    assert len(seen) == 600


def test_wire_servers_survive_garbage_bytes():
    """Malformed frames on the TCP ports must drop that connection, never
    kill the server: subsequent well-formed clients keep working."""
    import random
    import socket

    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.wire import MqttClient, MqttServer

    rng = random.Random(11)
    broker = Broker()
    broker.produce("t", b"v")
    with KafkaWireServer(broker) as ksrv:
        for _ in range(10):
            s = socket.create_connection(("127.0.0.1", ksrv.port), timeout=2)
            s.sendall(rng.randbytes(rng.randint(1, 64)))
            s.close()
        client = KafkaWireBroker(f"127.0.0.1:{ksrv.port}")
        assert [m.value for m in client.fetch("t", 0, 0)] == [b"v"]
        client.close()

    mbroker = MqttBroker()
    with MqttServer(mbroker) as msrv:
        for _ in range(10):
            s = socket.create_connection(("127.0.0.1", msrv.port), timeout=2)
            s.sendall(rng.randbytes(rng.randint(1, 64)))
            s.close()
        got = []
        c = MqttClient("127.0.0.1", msrv.port, "ok",
                       on_message=lambda t, p: got.append(p))
        c.subscribe("x", qos=0)
        mbroker.publish("x", b"alive", qos=0)
        deadline = __import__("time").time() + 5
        while not got and __import__("time").time() < deadline:
            __import__("time").sleep(0.05)
        assert got == [b"alive"]
        c.disconnect()


def test_firehose_publisher_bounded_broker_memory():
    """Overload protection under a firehose (VERDICT r1 item 6): a
    publisher blasting a stalled subscriber must be throttled by the
    watermarks — the broker's delivery backlog stays bounded instead of
    OOMing — while the stream keeps flowing end to end."""
    import socket as socket_mod
    import time

    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.eventserver import MqttEventServer
    from iotml.mqtt.wire import MqttClient, connect_packet, subscribe_packet

    mqtt_broker = MqttBroker()
    high, low, cap = 1 << 20, 256 * 1024, 8 << 20
    with MqttEventServer(mqtt_broker, max_outbuf=cap, high_watermark=high,
                         low_watermark=low, stall_timeout_s=2.0) as srv:
        # stalled subscriber (small window negotiated at SYN time)
        sub = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        sub.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVBUF, 4096)
        sub.settimeout(10)
        sub.connect(("127.0.0.1", srv.port))
        sub.sendall(connect_packet("stalled"))
        buf = b""
        while len(buf) < 4:
            buf += sub.recv(4 - len(buf))
        sub.sendall(subscribe_packet(1, [("vehicles/#", 0)]))
        time.sleep(0.2)

        pub = MqttClient("127.0.0.1", srv.port, "firehose")
        payload = b"x" * 16384
        peak = [0]
        done = threading.Event()

        def sample():
            while not done.is_set():
                peak[0] = max(peak[0], srv._total_out)
                time.sleep(0.005)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()
        for _ in range(2000):  # ~32 MB >> high watermark
            pub.publish("vehicles/sensor/data/car-1", payload, qos=0)
        done.set()
        sampler.join(timeout=5)

        # bounded: the backlog never exceeded the high watermark by more
        # than one read chunk + one in-flight fan-out burst
        slack = 1 << 20
        assert peak[0] <= high + slack, \
            f"backlog peaked at {peak[0]} (> {high} + {slack}): " \
            f"backpressure failed to bound memory"
        # ... and the system is alive (stalled sub evicted or throttled,
        # publisher still served)
        pub.publish("vehicles/sensor/data/car-1", b"final", qos=1)
        pub.disconnect()
        sub.close()


def test_close_storm_zero_loss_event_front():
    """Deterministic connect/publish/close churn on the epoll front: every
    qos-0 publish written before a clean close() must reach the bridge.

    This pins the once-seen 'zombie connection' tail loss: under burst
    load the listener's receive buffers overflowed on loopback, the
    kernel dropped segments, and the closing senders fell into RTO
    exponential backoff (observed rto ~29s, cwnd 1) — reading as lost
    messages to any drain that gives up earlier.  Deep listener rcvbuf +
    multi-chunk reads keep the flows out of backoff, and frames that
    arrive with the FIN are parsed before the close."""
    import socket as socket_mod

    from iotml.mqtt.bridge import KafkaBridge
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.eventserver import MqttEventServer
    from iotml.mqtt.wire import CONNACK, connect_packet, publish_packet
    from iotml.stream.broker import Broker

    mqtt_broker = MqttBroker()
    stream = Broker()
    stream.create_topic("sensor-data", partitions=4)
    bridge = KafkaBridge(mqtt_broker, stream, partitions=4)
    sent_counts = [0] * 4  # per-worker: summed after join (no shared +=)
    stop = threading.Event()
    errors: list = []

    def churn(w):
        payload = b"p" * 200
        try:
            for round_ in range(30):
                socks = []
                for i in range(20):
                    s = socket_mod.create_connection(
                        ("127.0.0.1", srv.port), timeout=10)
                    s.sendall(connect_packet(f"storm-{w}-{round_}-{i}"))
                    buf = b""
                    while len(buf) < 4:
                        chunk = s.recv(4 - len(buf))
                        if not chunk:
                            raise ConnectionError("EOF before CONNACK")
                        buf += chunk
                    assert buf[0] >> 4 == CONNACK
                    socks.append(s)
                for s in socks:
                    # burst then IMMEDIATE close — the storm shape
                    s.sendall(publish_packet(
                        f"vehicles/sensor/data/s{w}", payload) * 25)
                    sent_counts[w] += 25
                    s.close()
        except Exception as e:  # noqa: BLE001 - surfaced in the assert
            errors.append(repr(e))

    with MqttEventServer(mqtt_broker) as srv:
        threads = [threading.Thread(target=churn, args=(w,), daemon=True)
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "churn worker hung"
        assert not errors, errors
        total = sum(sent_counts)
        deadline = time.time() + 60
        while bridge.forwarded() < total and time.time() < deadline:
            time.sleep(0.02)
        assert bridge.forwarded() == total, \
            f"lost {total - bridge.forwarded()} of {total} in close-storm"


def test_close_storm_zero_loss_native_front():
    """The same storm against the C++ ingest engine."""
    import socket as socket_mod

    from iotml.mqtt.native_ingest import NativeIngestBridge
    from iotml.mqtt.wire import CONNACK, connect_packet, publish_packet
    from iotml.stream.broker import Broker

    pytest.importorskip("ctypes")
    from iotml.stream.native import available
    if not available():
        pytest.skip("native engine unavailable")

    stream = Broker()
    stream.create_topic("sensor-data", partitions=4)
    sent_counts = [0] * 4  # per-worker: summed after join (no shared +=)
    errors: list = []

    def churn(w, port):
        payload = b"p" * 200
        try:
            for round_ in range(30):
                socks = []
                for i in range(20):
                    s = socket_mod.create_connection(
                        ("127.0.0.1", port), timeout=10)
                    s.sendall(connect_packet(f"storm-{w}-{round_}-{i}"))
                    buf = b""
                    while len(buf) < 4:
                        chunk = s.recv(4 - len(buf))
                        if not chunk:
                            raise ConnectionError("EOF before CONNACK")
                        buf += chunk
                    assert buf[0] >> 4 == CONNACK
                    socks.append(s)
                for s in socks:
                    s.sendall(publish_packet(
                        f"vehicles/sensor/data/s{w}", payload) * 25)
                    sent_counts[w] += 25
                    s.close()
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    with NativeIngestBridge(stream, partitions=4) as bridge:
        threads = [threading.Thread(target=churn, args=(w, bridge.port),
                                    daemon=True) for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "churn worker hung"
        assert not errors, errors
        total = sum(sent_counts)
        deadline = time.time() + 60
        while bridge.forwarded() < total and time.time() < deadline:
            time.sleep(0.02)
        assert bridge.forwarded() == total, \
            f"lost {total - bridge.forwarded()} of {total} in close-storm"
