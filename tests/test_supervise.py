"""iotml.supervise — supervised lifecycles, fenced failover, live drills.

The live self-healing runtime (ISSUE 4): supervisor restart/degrade
semantics, the thread registry + lint R8 discipline, fenced leader
promotion over the wire protocol (epoch stamping both directions), the
replica's pause/resume barrier and live lag gauge, the streamproc
dead-letter queue, and the end-to-end drills with recovery SLOs.
"""

import json
import threading
import time
import urllib.request

import pytest

from iotml.obs import metrics as obs_metrics
from iotml.supervise import registry
from iotml.supervise.supervisor import (CRASHED, DEGRADED, FAILED_OVER,
                                        RUNNING, STOPPED, Supervisor)
from iotml.supervise.topology import Topology


def _wait_for(cond, timeout_s=10.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


# ------------------------------------------------------------- registry
def test_register_thread_enforces_daemon_and_name():
    ok = registry.register_thread(
        threading.Thread(target=lambda: None, daemon=True,
                         name="iotml-test-worker"))
    assert ok.name == "iotml-test-worker"
    with pytest.raises(ValueError):  # non-daemon refused
        registry.register_thread(
            threading.Thread(target=lambda: None, name="iotml-x"))
    with pytest.raises(ValueError):  # default Thread-N name refused
        registry.register_thread(
            threading.Thread(target=lambda: None, daemon=True))


def test_registry_tracks_live_threads():
    stop = threading.Event()
    t = registry.register_thread(
        threading.Thread(target=stop.wait, daemon=True,
                         name="iotml-test-live"))
    t.start()
    try:
        assert any(x.name == "iotml-test-live" for x in registry.threads())
    finally:
        stop.set()
        t.join(timeout=5)


# ----------------------------------------------------------- supervisor
def test_loop_unit_restarts_after_crash():
    runs = []

    def loop(unit):
        runs.append(1)
        if len(runs) == 1:
            raise RuntimeError("first incarnation dies")
        while not unit.should_stop():
            unit.heartbeat()
            time.sleep(0.01)

    with Supervisor(poll_interval_s=0.01) as sup:
        u = sup.add_loop("flappy", loop)
        assert _wait_for(lambda: u.restarts >= 1 and u.state == RUNNING)
        assert len(runs) == 2
        assert u.last_error == "RuntimeError: first incarnation dies"
    assert obs_metrics.supervisor_restarts.value(unit="flappy") >= 1


def test_restart_storm_budget_gives_up_degraded():
    def loop(unit):
        raise RuntimeError("always dies")

    with Supervisor(poll_interval_s=0.01) as sup:
        u = sup.add_loop("doomed", loop, max_restarts=3,
                         restart_window_s=30.0)
        assert _wait_for(lambda: u.state == DEGRADED)
        # budget spent, then the supervisor STOPPED retrying
        assert u.restarts == 3
        assert sup.degraded() == ["doomed"]
        assert obs_metrics.supervisor_degraded.value(unit="doomed") == 1
        time.sleep(0.1)
        assert u.restarts == 3  # no restarts after giving up


def test_clean_stop_is_not_a_crash():
    def loop(unit):
        while not unit.should_stop():
            unit.heartbeat()
            time.sleep(0.005)

    sup = Supervisor(poll_interval_s=0.01).start()
    u = sup.add_loop("steady", loop)
    assert _wait_for(lambda: u.state == RUNNING and u.alive())
    sup.stop()
    assert u.state == STOPPED and u.restarts == 0


def test_loop_returning_normally_is_a_clean_stop_not_a_crash():
    def loop(unit):
        unit.heartbeat()  # finite work, then a normal return

    with Supervisor(poll_interval_s=0.01) as sup:
        u = sup.add_loop("finite", loop)
        assert _wait_for(lambda: u.state == STOPPED)
        assert u.restarts == 0 and u.last_error is None


def test_wedged_unit_detected_and_replaced():
    wedge = threading.Event()
    incarnations = []

    def loop(unit):
        incarnations.append(unit)
        unit.heartbeat()
        if len(incarnations) == 1:
            wedge.wait(30)  # alive but silent: no more heartbeats
            return
        while not unit.should_stop():
            unit.heartbeat()
            time.sleep(0.01)

    try:
        with Supervisor(poll_interval_s=0.02) as sup:
            u = sup.add_loop("sticky", loop, heartbeat_timeout_s=0.15)
            assert _wait_for(lambda: u.restarts >= 1 and len(incarnations) >= 2)
            assert obs_metrics.supervisor_wedged.value(unit="sticky") >= 1
    finally:
        wedge.set()


def test_probed_unit_on_death_fires_failover_once():
    alive = {"ok": True}
    fired = []

    with Supervisor(poll_interval_s=0.01) as sup:
        u = sup.add_probed("leader", lambda: alive["ok"],
                           on_death=fired.append, probe_failures=2)
        assert _wait_for(lambda: u.state == RUNNING)
        alive["ok"] = False
        assert _wait_for(lambda: u.state == FAILED_OVER)
        time.sleep(0.1)  # further ticks must not re-fire the hook
        assert fired == [u]
        assert obs_metrics.supervisor_failovers.value(unit="leader") >= 1


def test_probed_unit_restart_fn_recovers():
    state = {"up": True}

    def restart():
        state["up"] = True

    with Supervisor(poll_interval_s=0.01) as sup:
        u = sup.add_probed("svc", lambda: state["up"], restart=restart,
                           probe_failures=2)
        assert _wait_for(lambda: u.state == RUNNING)
        state["up"] = False
        assert _wait_for(lambda: u.restarts >= 1 and state["up"])
        assert _wait_for(lambda: u.state == RUNNING)


def test_supervise_toggles_never_leak_into_config_tree():
    """IOTML_SUPERVISE* are process toggles in config's non_config set:
    the resolver must neither reject them (typo'd IOTML_ vars fail
    loudly by design) nor apply them anywhere in the config tree."""
    from iotml.config import load_config

    cfg, _ = load_config(argv=[], env={
        "IOTML_SUPERVISE": "1", "IOTML_SUPERVISE_POLL_S": "0.2",
        "IOTML_SUPERVISE_MAX_RESTARTS": "9"})
    clean, _ = load_config(argv=[], env={})
    assert cfg.as_dict() == clean.as_dict()
    assert cfg.applied == set()


def test_supervise_env_knobs_are_read(monkeypatch):
    monkeypatch.setenv("IOTML_SUPERVISE_MAX_RESTARTS", "2")
    monkeypatch.setenv("IOTML_SUPERVISE_POLL_S", "0.123")
    from iotml.supervise.supervisor import SupervisedUnit

    u = SupervisedUnit("env-unit", lambda unit: None)
    assert u.max_restarts == 2
    assert Supervisor().poll_interval_s == 0.123


# ------------------------------------------------------------- topology
def test_topology_publish_monotonic_and_resolve_order():
    topo = Topology("a:1", epoch=0, fallback=["b:2"])
    assert topo.resolve() == (["a:1", "b:2"], 0)
    topo.publish("b:2", 1)
    servers, epoch = topo.resolve()
    assert servers[0] == "b:2" and "a:1" in servers and epoch == 1
    with pytest.raises(ValueError):
        topo.publish("a:1", 0)  # epochs only move forward


# -------------------------------------------------------- epoch fencing
def _wire_pair(epoch=0):
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer

    broker = Broker()
    broker.create_topic("T", partitions=1)
    srv = KafkaWireServer(broker, epoch=epoch).start()
    return broker, srv


def test_stale_client_is_fenced_on_produce_and_commit():
    from iotml.stream.kafka_wire import FencedEpochError, KafkaWireBroker

    broker, srv = _wire_pair(epoch=2)
    try:
        stale = KafkaWireBroker(f"127.0.0.1:{srv.port}", epoch=1)
        with pytest.raises(FencedEpochError):
            stale.produce("T", b"x")
        with pytest.raises(FencedEpochError):
            stale.commit("g", "T", 0, 5)
        assert broker.end_offset("T", 0) == 0      # nothing appended
        assert broker.committed("g", "T", 0) is None
        # reads stay open to any epoch (consumers drain across terms)
        assert stale.end_offset("T", 0) == 0
        # legacy unstamped clients pass unfenced (standard Kafka client
        # compatibility: the tag is absent, not wrong)
        legacy = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        legacy.produce("T", b"y")
        assert broker.end_offset("T", 0) == 1
        legacy.close()
        stale.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_resurrected_old_leader_is_fenced():
    """The other direction: the SERVER is the stale party (epoch 0 after
    a crash-restart), the client carries the post-promotion epoch."""
    from iotml.stream.kafka_wire import FencedEpochError, KafkaWireBroker

    broker, srv = _wire_pair(epoch=0)
    try:
        current = KafkaWireBroker(f"127.0.0.1:{srv.port}", epoch=1)
        with pytest.raises(FencedEpochError):
            current.produce("T", b"split-brain")
        assert broker.end_offset("T", 0) == 0
        current.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_reresolves_topology_after_fence():
    from iotml.stream.kafka_wire import FencedEpochError, KafkaWireBroker

    broker_a, srv_a = _wire_pair(epoch=0)
    broker_b, srv_b = _wire_pair(epoch=1)
    topo = Topology(f"127.0.0.1:{srv_a.port}", epoch=0,
                    fallback=[f"127.0.0.1:{srv_b.port}"])
    try:
        client = KafkaWireBroker(f"127.0.0.1:{srv_a.port}", topology=topo)
        client.produce("T", b"term0")
        assert broker_a.end_offset("T", 0) == 1
        # promotion happens elsewhere: topology now names B at epoch 1
        topo.publish(f"127.0.0.1:{srv_b.port}", 1)
        srv_a.set_epoch(2)  # A is now stale relative to this client
        with pytest.raises(FencedEpochError):
            client.produce("T", b"stale")
        # the fence re-resolved: the SAME client now writes to B at
        # epoch 1 without being rebuilt
        client.produce("T", b"term1")
        assert broker_b.end_offset("T", 0) == 1
        assert client.epoch == 1
        client.close()
    finally:
        for s in (srv_a, srv_b):
            s.shutdown()
            s.server_close()


# ------------------------------------------------- replica promote/pause
def test_follower_fenced_until_promoted_then_serves():
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import (FencedEpochError, KafkaWireBroker,
                                         KafkaWireServer)
    from iotml.stream.replica import FollowerReplica

    leader = Broker()
    leader.create_topic("T")
    for i in range(5):
        leader.produce("T", f"m{i}".encode())
    lsrv = KafkaWireServer(leader).start()
    rep = FollowerReplica(f"127.0.0.1:{lsrv.port}", topics=["T"])
    rep.server.start()
    try:
        while rep.sync_once() > 0:
            pass
        stamped = KafkaWireBroker(f"127.0.0.1:{rep.port}", epoch=0)
        with pytest.raises(FencedEpochError):
            # pre-promotion the follower is NOT a leader: an
            # epoch-stamped produce must not fork the replicated log
            stamped.produce("T", b"fork")
        addr = rep.promote(3)
        assert rep.promoted and addr.endswith(f":{rep.port}")
        assert obs_metrics.failover_epoch.value() == 3
        promoted_client = KafkaWireBroker(f"127.0.0.1:{rep.port}", epoch=3)
        off = promoted_client.produce("T", b"post-failover")
        assert off == 5  # appended right after the mirrored log
        with pytest.raises(RuntimeError):
            rep.promote(4)  # promotion is once
        promoted_client.close()
        stamped.close()
    finally:
        rep.server.shutdown()
        rep.server.server_close()
        lsrv.shutdown()
        lsrv.server_close()


def test_pause_resume_is_a_real_barrier():
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.replica import FollowerReplica

    leader = Broker()
    leader.create_topic("T")
    leader.produce("T", b"a")
    lsrv = KafkaWireServer(leader).start()
    rep = FollowerReplica(f"127.0.0.1:{lsrv.port}", topics=["T"],
                          poll_interval_s=0.005).start()
    try:
        assert rep.caught_up(timeout_s=10)
        assert rep.pause()
        rounds = rep.rounds
        leader.produce("T", b"b")
        time.sleep(0.1)
        # parked: the background loop ran no round, so the new record
        # is NOT mirrored until someone syncs explicitly
        assert rep.rounds == rounds
        assert rep.local.end_offset("T", 0) == 1
        rep.sync_once()
        assert rep.local.end_offset("T", 0) == 2
        rep.resume()
        leader.produce("T", b"c")
        assert _wait_for(lambda: rep.local.end_offset("T", 0) == 3)
    finally:
        rep.stop()
        lsrv.shutdown()
        lsrv.server_close()


def test_replica_lag_gauge_is_live():
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.replica import FollowerReplica

    leader = Broker()
    leader.create_topic("lagT")
    for i in range(7):
        leader.produce("lagT", b"x")
    lsrv = KafkaWireServer(leader).start()
    rep = FollowerReplica(f"127.0.0.1:{lsrv.port}", topics=["lagT"],
                          poll_interval_s=0.005, commit_interval_s=0.01)
    try:
        rep.sync_once()
        assert rep.lag() == {"lagT": 0}
        assert obs_metrics.replica_lag.value(topic="lagT") == 0
        leader.produce("lagT", b"y")
        assert rep.lag() == {"lagT": 1}
        assert obs_metrics.replica_lag.value(topic="lagT") == 1
        # the background loop probes the gauge on its own cadence
        rep.start()
        assert _wait_for(
            lambda: obs_metrics.replica_lag.value(topic="lagT") == 0)
    finally:
        rep.stop()
        lsrv.shutdown()
        lsrv.server_close()


# --------------------------------------------------------------- healthz
def test_healthz_reports_supervisor_and_failover_state():
    def loop(unit):
        while not unit.should_stop():
            unit.heartbeat()
            time.sleep(0.005)

    srv = obs_metrics.start_http_server(port=0)
    sup = Supervisor(poll_interval_s=0.01).start()
    try:
        sup.add_loop("healthz-probe-unit", loop)
        obs_metrics.failover_epoch.set(2)
        obs_metrics.replica_lag.set(4, topic="T")
        _wait_for(lambda: sup.unit("healthz-probe-unit").alive())
        port = srv.server_address[1]
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        assert "healthz-probe-unit" in doc["supervisor"]
        assert doc["supervisor"]["healthz-probe-unit"]["state"] == RUNNING
        assert doc["failover_epoch"] == 2
        assert doc["replica_lag_records"]["T"] == 4
        # the metrics endpoint exports the same families
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "iotml_supervisor_unit_up" in body
        assert "iotml_failover_epoch 2" in body
    finally:
        sup.stop()
        srv.shutdown()
        srv.server_close()
        obs_metrics.failover_epoch.set(0)


# ------------------------------------------------------------------- DLQ
def test_json_to_avro_dead_letters_poisoned_records():
    from iotml.stream.broker import Broker
    from iotml.streamproc.dlq import decode_envelope, dlq_topic
    from iotml.streamproc.tasks import JsonToAvro

    broker = Broker()
    broker.create_topic("sensor-data")
    task = JsonToAvro(broker, src="sensor-data", dst="J2A_OUT")
    good = {"coolant_temp": 1.0, "intake_air_temp": 2.0}
    before = obs_metrics.dlq_total.value(source="sensor-data")
    broker.produce("sensor-data", json.dumps(good).encode(), key=b"car1")
    broker.produce("sensor-data", b"{not json", key=b"car2")
    broker.produce("sensor-data", b'["array", "not", "object"]')
    broker.produce("sensor-data",
                   json.dumps({"coolant_temp": "NaN-ish-text"}).encode())
    n = task.process_available()
    assert n == 1  # the good record flowed; poison did not halt it
    dlq = dlq_topic("sensor-data")
    assert dlq in broker.topics()
    letters = [decode_envelope(m.value)
               for m in broker.fetch(dlq, 0, 0, 100)]
    assert len(letters) == 3
    assert {d["task"] for d in letters} == {"JsonToAvro"}
    by_raw = {d["raw"] for d in letters}
    assert b"{not json" in by_raw
    assert all(d["source"] == "sensor-data" for d in letters)
    assert all("error" in d and d["error"] for d in letters)
    assert obs_metrics.dlq_total.value(source="sensor-data") == before + 3


def test_delimited_to_avro_dead_letters_but_skips_header():
    from iotml.core.schema import CAR_SCHEMA
    from iotml.stream.broker import Broker
    from iotml.streamproc.dlq import dlq_topic
    from iotml.streamproc.tasks import DelimitedToAvro

    broker = Broker()
    broker.create_topic("car-data-csv")
    task = DelimitedToAvro(broker, src="car-data-csv", dst="CSV_OUT")
    n_cols = 2 + len(CAR_SCHEMA.fields)
    header = ",".join(["time", "car"] + ["c"] * (n_cols - 2))
    good = ",".join(["1", "car9"] + ["1.5"] * (n_cols - 2))
    broker.produce("car-data-csv", header.encode())   # expected: skipped
    broker.produce("car-data-csv", good.encode())
    broker.produce("car-data-csv", b"\xff\xfe\xff")   # bad utf-8
    broker.produce("car-data-csv", b"1,car1,too,short")
    broker.produce("car-data-csv",
                   ",".join(["1", "car2"] + ["xyz"] * (n_cols - 2)).encode())
    assert task.process_available() == 1
    letters = broker.fetch(dlq_topic("car-data-csv"), 0, 0, 100)
    assert len(letters) == 3  # header line is NOT poison


def test_sql_engine_select_task_dead_letters_undecodable_avro():
    from iotml.stream.broker import Broker
    from iotml.streamproc import SqlEngine
    from iotml.streamproc.dlq import decode_envelope, dlq_topic
    from iotml.streamproc.sql import install_reference_pipeline

    broker = Broker()
    broker.create_topic("sensor-data", partitions=1)
    engine = SqlEngine(broker)
    install_reference_pipeline(engine)
    good = {"coolant_temp": 3.3, "car": "car1"}
    broker.produce("sensor-data", json.dumps(good).encode(), key=b"car1")
    broker.produce("sensor-data", b"\x00garbage-not-json", key=b"car2")
    engine.pump()
    dlq = dlq_topic("sensor-data")
    assert dlq in broker.topics()
    letters = [decode_envelope(m.value)
               for m in broker.fetch(dlq, 0, 0, 100)]
    assert any(d["raw"] == b"\x00garbage-not-json" for d in letters)
    # the AVRO leg still produced the good record
    assert broker.end_offset("SENSOR_DATA_S_AVRO", 0) >= 1


def test_obs_dlq_cli_peeks_over_the_wire(capsys):
    from iotml.obs.__main__ import main as obs_main
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.streamproc.tasks import JsonToAvro

    broker = Broker()
    broker.create_topic("sensor-data")
    task = JsonToAvro(broker, src="sensor-data", dst="J2A_OUT2")
    broker.produce("sensor-data", b"not json at all", key=b"carX")
    task.process_available()
    # non-envelope garbage on the open DLQ topic (valid JSON non-object
    # included) must render as a fallback row, never crash the CLI
    broker.produce("sensor-data_DLQ", b"[1]")
    broker.produce("sensor-data_DLQ", b"not even json")
    srv = KafkaWireServer(broker).start()
    try:
        rc = obs_main(["dlq", "--bootstrap", f"127.0.0.1:{srv.port}",
                       "--topic", "sensor-data"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "sensor-data_DLQ" in out and "JsonToAvro" in out
        assert "not json at all" in out
        # missing DLQ topic is a clean empty answer, not an error
        rc = obs_main(["dlq", "--bootstrap", f"127.0.0.1:{srv.port}",
                       "--topic", "never-poisoned"])
        assert rc == 0
        assert "does not exist" in capsys.readouterr().out
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------------------ lint
def test_lint_r8_fixture_findings():
    import os

    from iotml.analysis.lint import lint_file

    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "analysis", "bad_thread.py")
    findings = [f for f in lint_file(fixture) if f.rule == "R8"]
    # fire_and_forget (all three problems), named_but_unregistered
    # (wrapper only), aliased_evasion (t.Thread dodge — wrapper only)
    assert len(findings) == 3
    msgs = {f.line: f.message for f in sorted(findings,
                                              key=lambda f: f.line)}
    lines = sorted(msgs)
    assert "daemon=True" in msgs[lines[0]] and "name=" in msgs[lines[0]] \
        and "register_thread" in msgs[lines[0]]
    for ln in lines[1:]:
        assert "register_thread" in msgs[ln]
        assert "daemon" not in msgs[ln]


def test_lint_r8_clean_on_production_tree():
    from iotml.analysis.lint import default_root, lint_paths

    r8 = [f for f in lint_paths([default_root()], rules={"R8"})]
    assert r8 == [], "\n".join(str(f) for f in r8)


# ---------------------------------------------------------- live drills
def test_live_drill_scorer_crash_heals():
    from iotml.supervise.drill import drill_scorer_crash

    report = drill_scorer_crash(seed=11, records=300)
    assert report.ok, "\n".join(report.lines())
    assert report.restarts["scorer"] >= 1
    assert report.scored >= report.published


def test_live_drill_leader_kill_promotes_and_fences():
    from iotml.supervise.drill import drill_leader_kill

    report = drill_leader_kill(seed=5, records=400)
    assert report.ok, "\n".join(report.lines())
    by_name = {i.name: i for i in report.invariants}
    assert by_name["old_leader_fenced"].ok
    assert by_name["promotion_loss_bounded"].ok
    assert report.slos["time_to_promote_s"] is not None
    assert report.slos["time_to_promote_s"] <= 10.0


def test_drill_cli_list_and_unknown(capsys):
    from iotml.supervise.__main__ import main as sup_main

    assert sup_main(["list"]) == 0
    out = capsys.readouterr().out
    for name in ("leader-kill", "mqtt-flap", "scorer-crash"):
        assert name in out
    assert sup_main(["drill", "--drill", "no-such-drill"]) == 2


# --------------------------------------------------- platform supervision
def test_platform_supervised_restarts_dead_pump():
    from iotml.cli.up import Platform

    plat = Platform(partitions=2)
    plat.start()
    sup = plat.supervised(poll_interval_s=0.02).start()
    try:
        names = {u.name for u in sup.units()}
        assert {"kafka-wire", "mqtt-front", "ksql-tasks",
                "connect-driver"} <= names
        assert _wait_for(
            lambda: sup.unit("ksql-tasks").state == RUNNING)
        # kill the continuous-query pump thread the way a bug would:
        # stop flag set, thread exits, nobody restarts it by hand
        plat.ksql._stop.set()
        assert _wait_for(lambda: sup.unit("ksql-tasks").restarts >= 1)
        plat.ksql._stop.clear()
        assert _wait_for(
            lambda: plat.ksql._pump_thread.is_alive()
            and sup.unit("ksql-tasks").state == RUNNING)
    finally:
        sup.stop()
        plat.stop()
