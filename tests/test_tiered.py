"""iotml.store.tiered — object-store tiered log storage (ISSUE 18).

Sealed segments offload to an ArtifactStore-backed remote tier through
a manifest-as-commit-marker protocol; local retention becomes a hot
cache; every read API falls through to the remote tier transparently.
Pinned here: the fall-through is byte-identical to pre-trim replay,
a kill mid-upload never yields a servable torn remote segment, the
consumer never counts a remote-tier read as an auto-reset, the remote
leg rides the SAME frame scanner / columnar decoder as local reads
(call-counted), quorum-HWM bytes never tier out, and the ArtifactStore
local/GCS backends behave identically (parity harness)."""

import json
import os

import pytest

from iotml.obs import metrics as obs_metrics
from iotml.store import (RemoteSegmentCache, RemoteTier, SegmentedLog,
                         StorePolicy, TieredLog, TierPolicy, TierUploader)
from iotml.store import segment as seg_mod
from iotml.stream.broker import Broker, OffsetOutOfRangeError
from iotml.stream.consumer import StreamConsumer
from iotml.train.artifacts import ArtifactStore


def _tiered(tmp_path, segment_bytes=512, **tier_kw):
    """A standalone TieredLog over a local-directory 'bucket'."""
    store = ArtifactStore(str(tmp_path / "bucket"))
    remote = RemoteTier(store, prefix="tiered/T/0")
    log = TieredLog(str(tmp_path / "local"),
                    policy=StorePolicy(fsync="never",
                                       segment_bytes=segment_bytes),
                    remote=remote,
                    tier=TierPolicy(uri=str(tmp_path / "bucket"), **tier_kw))
    return log, remote, store


def _fill(log, n, ts0=1000, payload=b"payload-"):
    for i in range(n):
        log.append(f"k{i % 7}".encode(), payload + str(i).encode(), ts0 + i)


def _dump(log):
    return log.read_from(log.base_offset, 10 ** 6)


# ----------------------------------------------------- artifact store
def test_artifact_store_local_list_delete_atomic(tmp_path):
    """Satellite 1: the hardened local backend — atomic upload (no
    staging tmp ever listed or left behind), prefix listing, idempotent
    delete."""
    st = ArtifactStore(str(tmp_path / "b"))
    src = tmp_path / "src.bin"
    src.write_bytes(b"x" * 100)
    st.upload(str(src), "a/one.log")
    st.upload(str(src), "a/two.log")
    st.put_text("a/manifest.json", "{}")
    st.put_text("other/three.txt", "t")
    assert st.list("a") == ["a/manifest.json", "a/one.log", "a/two.log"]
    assert st.list() == ["a/manifest.json", "a/one.log", "a/two.log",
                         "other/three.txt"]
    # no .tmp.<pid> staging artifact survives (or is ever listed)
    leftovers = [n for n in st.list() if ".tmp." in n]
    assert leftovers == []
    assert not any(".tmp." in f for _, _, fs in os.walk(st.root) for f in fs)
    assert st.delete("a/one.log") is True
    assert st.delete("a/one.log") is False  # idempotent
    assert st.list("a") == ["a/manifest.json", "a/two.log"]


class _FakeBlob:
    """google-cloud-storage blob duck backed by a shared dict."""

    def __init__(self, objects, name):
        self._objects, self.name = objects, name

    def upload_from_filename(self, path):
        with open(path, "rb") as fh:
            self._objects[self.name] = fh.read()

    def upload_from_string(self, text):
        self._objects[self.name] = text.encode()

    def download_to_filename(self, path):
        with open(path, "wb") as fh:
            fh.write(self._objects[self.name])

    def download_as_bytes(self):
        return self._objects[self.name]

    def exists(self):
        return self.name in self._objects

    def delete(self):
        del self._objects[self.name]


class _FakeBucket:
    def __init__(self, objects):
        self._objects = objects

    def blob(self, name):
        return _FakeBlob(self._objects, name)

    def list_blobs(self, prefix=""):
        return [_FakeBlob(self._objects, n) for n in sorted(self._objects)
                if n.startswith(prefix)]


def _gcs_store(objects, prefix="pfx"):
    st = ArtifactStore.__new__(ArtifactStore)
    st.root = "gs://bucket/" + prefix
    st._gcs = True
    st._prefix = prefix
    st._bucket = _FakeBucket(objects)
    return st


def test_artifact_store_gcs_local_parity(tmp_path):
    """Satellite 1: one operation script, two backends, identical
    observable behavior — list/get_text/exists/delete must not fork
    between the local directory and the (faked) GCS client."""
    local = ArtifactStore(str(tmp_path / "b"))
    gcs = _gcs_store({})
    src = tmp_path / "src.bin"
    src.write_bytes(b"blobbytes")

    def script(st):
        out = []
        st.upload(str(src), "t/0/seg.log")
        st.put_text("t/0/manifest.json", '{"v": 1}')
        out.append(st.list("t/0"))
        out.append(st.get_text("t/0/manifest.json"))
        out.append(st.get_text("t/0/missing"))
        out.append(st.exists("t/0/seg.log"))
        out.append(st.delete("t/0/seg.log"))
        out.append(st.delete("t/0/seg.log"))
        out.append(st.list())
        return out

    assert script(local) == script(gcs)


# ------------------------------------------------------- fall-through
def test_remote_fall_through_byte_identical_to_pre_trim(tmp_path):
    """The core satellite-4 contract: tier out, evict the hot tier,
    and the full replay is byte-identical to the pre-trim read."""
    log, _remote, _store = _tiered(tmp_path)
    _fill(log, 200)
    log.roll()
    before = _dump(log)
    assert len(before) == 200
    stats = log.tier_sync()
    assert stats["uploaded"] >= 2 and stats["bytes"] > 0
    served_before = obs_metrics.default_registry.counter(
        "iotml_tier_remote_records_total", "").value()
    assert log.evict_hot(budget_bytes=0) > 0
    assert log.local_base_offset > 0      # hot tier actually trimmed
    assert log.base_offset == 0           # ...but the LOG still starts at 0
    after = _dump(log)
    assert after == before
    assert obs_metrics.default_registry.counter(
        "iotml_tier_remote_records_total", "").value() > served_before
    # below the tiered base is still an explicit trimmed-history signal
    log2 = SegmentedLog(str(tmp_path / "plain"),
                        policy=StorePolicy(fsync="never"))
    log2.append(None, b"x", 1)
    with pytest.raises(LookupError):
        log.read_from(-1, 10)
    log.close()
    log2.close()


def test_timestamp_seek_and_read_since_span_tiers(tmp_path):
    """offset_for_timestamp / read_since answer identically before and
    after the head of the log moved to the remote tier."""
    log, _remote, _store = _tiered(tmp_path)
    _fill(log, 150, ts0=1000)
    log.roll()
    seek_pre = {ts: log.offset_for_timestamp(ts)
                for ts in (1000, 1010, 1075, 1149, 2000)}
    since_pre = log.read_since(1010, max_records=10 ** 6)
    log.tier_sync()
    log.evict_hot(budget_bytes=0)
    assert log.local_base_offset > 10  # the seek targets now live remotely
    seek_post = {ts: log.offset_for_timestamp(ts) for ts in seek_pre}
    assert seek_post == seek_pre
    assert log.read_since(1010, max_records=10 ** 6) == since_pre
    log.close()


def test_cold_mount_serves_remote_history(tmp_path):
    """Follower bootstrap: a fresh empty local dir over an existing
    remote tier replays the committed history."""
    log, _remote, store = _tiered(tmp_path)
    _fill(log, 120)
    log.roll()
    log.tier_sync()
    committed_end = max(m.next for m in log.remote_metas())
    want = [r for r in _dump(log) if r[0] < committed_end]
    log.close()
    cold = TieredLog(str(tmp_path / "cold"),
                     policy=StorePolicy(fsync="never"),
                     remote=RemoteTier(store, prefix="tiered/T/0"),
                     tier=TierPolicy(uri=str(tmp_path / "bucket")))
    assert cold.base_offset == 0
    assert cold.read_from(0, 10 ** 6) == want
    cold.close()


# --------------------------------------------------- commit marker
def test_kill_mid_upload_serves_only_committed(tmp_path, monkeypatch):
    """Satellite 4: a crash between the blob uploads and the manifest
    commit leaves blobs no reader ever sees — a remount (and a cold
    manifest-only reader) serve exactly the committed prefix, and the
    local copy stays fully authoritative."""
    log, remote, store = _tiered(tmp_path)
    _fill(log, 200)
    log.roll()
    full = _dump(log)

    calls = {"n": 0}
    orig = RemoteTier._commit

    def dying_commit(self, metas):
        if calls["n"] >= 2:
            raise OSError("killed mid-upload")
        calls["n"] += 1
        return orig(self, metas)

    monkeypatch.setattr(RemoteTier, "_commit", dying_commit)
    with pytest.raises(OSError):
        log.tier_sync()
    monkeypatch.setattr(RemoteTier, "_commit", orig)

    committed = log.remote_metas()
    assert len(committed) == 2  # the prefix that committed before the kill
    committed_end = max(m.next for m in committed)
    # torn remote footprint exists (blobs + stage marker), unreferenced
    listed = store.list("tiered/T/0")
    referenced = {f"tiered/T/0/manifest.json"}
    for m in committed:
        for sfx in (".log", ".index", ".timeindex"):
            referenced.add(f"tiered/T/0/{m.base:020d}{sfx}")
    torn = [n for n in listed if n not in referenced]
    assert torn  # the kill left garbage...
    # ...which no reader serves: a cold manifest-only mount stops at
    # the committed end
    cold = TieredLog(str(tmp_path / "cold"),
                     policy=StorePolicy(fsync="never"),
                     remote=RemoteTier(store, prefix="tiered/T/0"),
                     tier=TierPolicy(uri=str(tmp_path / "bucket")))
    got = cold.read_from(0, 10 ** 6)
    assert got == [r for r in full if r[0] < committed_end]
    cold.close()
    # local stays authoritative: retention/eviction refuse to drop the
    # uncommitted segment, the full log still re-serves
    assert _dump(log) == full
    evicted_bases_stop = log.evict_hot(budget_bytes=0)
    assert log.local_base_offset <= committed_end
    assert _dump(log) == full
    # the resumed pass commits the rest; the re-upload reclaims the
    # torn blob names (stage marker deleted, blobs overwritten) so the
    # prefix ends fully referenced with no garbage left
    stats = log.tier_sync()
    assert stats["uploaded"] >= 1
    assert [n for n in store.list("tiered/T/0") if n.endswith(".stage")] == []
    referenced_after = {"tiered/T/0/manifest.json"}
    for m in log.remote_metas():
        for sfx in (".log", ".index", ".timeindex"):
            referenced_after.add(f"tiered/T/0/{m.base:020d}{sfx}")
    assert set(store.list("tiered/T/0")) == referenced_after
    log.evict_hot(budget_bytes=0)
    assert _dump(log) == full
    log.close()
    del evicted_bases_stop


def test_torn_remote_blob_never_served(tmp_path):
    """A blob corrupted AFTER its commit (a lying backend) fails the
    size/CRC gate at fetch and reads as trimmed history, never as
    data."""
    log, remote, store = _tiered(tmp_path)
    _fill(log, 120)
    log.roll()
    log.tier_sync()
    log.evict_hot(budget_bytes=0)
    assert _dump(log)  # remote serving works...
    log.cache.clear()
    victim = log.remote_metas()[0]
    blob_path = os.path.join(store.root,
                             f"tiered/T/0/{victim.base:020d}.log")
    blob = bytearray(open(blob_path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(blob_path, "wb") as fh:
        fh.write(bytes(blob))
    with pytest.raises(LookupError):
        log.read_from(victim.base, 10)
    cache = RemoteSegmentCache(str(tmp_path / "c2"), max_segments=2)
    with pytest.raises(OSError):
        cache.get(victim, remote)
    log.close()


# ------------------------------------------------ consumer integration
def test_consumer_poll_below_local_base_no_autoreset(tmp_path):
    """Satellite 2: a poll at an offset that lives only in the remote
    tier is a normal read — NOT an out-of-range auto-reset.  The
    auto-reset counter must not move and the cursor must not jump."""
    broker = Broker(store_dir=str(tmp_path / "store"),
                    store_policy=StorePolicy(fsync="never",
                                             segment_bytes=1024),
                    tier=TierPolicy(uri=str(tmp_path / "bucket")))
    broker.create_topic("T", partitions=1)
    for i in range(100):
        broker.produce("T", b"value-%d" % i, key=b"k", timestamp_ms=i)
    log = broker.store.log_for("T", 0)
    log.roll()
    broker.run_tiering()
    log.evict_hot(budget_bytes=0)
    assert log.local_base_offset > 0
    assert broker.begin_offset("T", 0) == 0  # the broker sees one log
    before = obs_metrics.consumer_autoresets.value(topic="T")
    consumer = StreamConsumer(broker, ["T:0:0"], group="tier-g")
    got = []
    for _ in range(50):
        batch = consumer.poll(64)
        if not batch:
            break
        got.extend(batch)
    assert [m.offset for m in got] == list(range(100))
    assert [m.value for m in got] == [b"value-%d" % i for i in range(100)]
    assert obs_metrics.consumer_autoresets.value(topic="T") == before
    # a read below the TIERED base is still a real auto-reset signal
    with pytest.raises(OffsetOutOfRangeError):
        broker.fetch("T", 0, -5, 10)
    broker.close()


def test_remote_read_rides_the_one_frame_scanner(tmp_path, monkeypatch):
    """The one-decoder pin (non-native half): remote-tier reads go
    through the SAME seg.iter_frames scanner as local reads — counted,
    and observed operating on .tiercache (remote) segment files."""
    log, _remote, _store = _tiered(tmp_path)
    _fill(log, 150)
    log.roll()
    log.tier_sync()
    log.evict_hot(budget_bytes=0)
    log.cache.clear()
    _fill(log, 5, ts0=5000)  # a fresh local tail after the eviction
    seen = []
    orig = seg_mod.iter_frames

    def counting(path, start_pos=0):
        seen.append(path)
        return orig(path, start_pos)

    monkeypatch.setattr(seg_mod, "iter_frames", counting)
    out = log.read_from(0, 10 ** 6)
    assert len(out) == 155
    assert seen, "reads bypassed the one frame scanner"
    remote_reads = [p for p in seen if ".tiercache" in p]
    local_reads = [p for p in seen if ".tiercache" not in p]
    assert remote_reads, "remote leg did not ride seg.iter_frames"
    assert local_reads, "local tail should serve the batch end"
    log.close()


# -------------------------------------------------- tiering mechanics
def test_quorum_ceiling_bounds_tiering(tmp_path):
    """Only below-HWM sealed bytes tier out: segments whose
    next_offset exceeds the replication ceiling stay local-only."""
    log, _remote, _store = _tiered(tmp_path)
    _fill(log, 150)
    log.roll()
    sealed = log.segments()[:-1] if hasattr(log, "segments") else None
    ceiling = 60
    stats = log.tier_sync(ceiling=ceiling)
    assert stats["uploaded"] >= 1
    assert all(m.next <= ceiling for m in log.remote_metas())
    # eviction honors the same line: nothing uncommitted drops
    log.evict_hot(budget_bytes=0)
    assert log.local_base_offset <= ceiling
    assert _dump(log)[0][0] == 0
    # the ceiling lifting lets the rest tier out
    log.tier_sync(ceiling=150)
    assert max(m.next for m in log.remote_metas()) > ceiling
    log.close()
    del sealed


def test_upload_lag_defers_fresh_seals(tmp_path):
    """tier.upload_lag_s: a freshly sealed segment waits (so a
    compaction pass can win the race); lag elapsed/zero uploads."""
    log, _remote, _store = _tiered(tmp_path, upload_lag_s=3600.0)
    _fill(log, 100)
    log.roll()
    stats = log.tier_sync()
    assert stats["uploaded"] == 0 and log.remote_metas() == []
    stats = log.tier_sync(upload_lag_s=0.0)
    assert stats["uploaded"] >= 1
    log.close()


def test_hot_byte_budget_and_remote_retention(tmp_path):
    """tier.local_hot_bytes evicts committed head segments past the
    budget; tier.remote_retention_ms ages remote segments out (manifest
    first, then blobs) and the tiered base rises accordingly."""
    log, remote, store = _tiered(tmp_path, local_hot_bytes=2048,
                                 remote_retention_ms=50)
    _fill(log, 200, ts0=1000)
    log.roll()
    log.tier_sync()
    assert log.total_bytes() <= 2048 + log.segments_bytes_last() \
        if hasattr(log, "segments_bytes_last") else True
    assert log.local_base_offset > 0
    # remote retention dropped everything older than newest-50ms
    metas = log.remote_metas()
    newest = 1000 + 199
    assert all(m.max_ts >= newest - 50 or m.max_ts < 0 for m in metas) \
        or metas == []
    # dropped blobs are actually gone from the bucket
    listed = store.list("tiered/T/0")
    for n in listed:
        if n.endswith(".log"):
            base = int(os.path.basename(n)[:-4])
            assert any(m.base == base for m in metas)
    # reads below the tiered base now signal trimmed history
    if log.base_offset > 0:
        with pytest.raises(LookupError):
            log.read_from(0, 1)
    log.close()


def test_compacted_rewrite_reuploads_same_base(tmp_path):
    """Compaction composes: a compacted rewrite of an uploaded segment
    invalidates its manifest coverage (size changed) and the next pass
    re-uploads the SAME base; reads stay correct through eviction."""
    log, _remote, _store = _tiered(tmp_path, segment_bytes=1024)
    for i in range(200):  # few keys, many shadowed versions
        log.append(b"k%d" % (i % 3), b"v-%d" % i, 1000 + i)
    log.roll()
    stats = log.tier_sync()
    assert stats["uploaded"] >= 1
    pre_bases = {m.base: m.size for m in log.remote_metas()}
    st = log.compact(grace_ms=0)
    assert st.segments_rewritten >= 1
    latest = {r[1]: r for r in _dump(log)}  # latest record per key
    stats2 = log.tier_sync()
    assert stats2["uploaded"] >= 1  # the rewrite re-uploaded
    post = {m.base: m.size for m in log.remote_metas()}
    changed = [b for b in post if b in pre_bases
               and post[b] != pre_bases[b]]
    assert changed, "no manifest entry was replaced by the rewrite"
    log.evict_hot(budget_bytes=0)
    assert {r[1]: r for r in _dump(log)} == latest
    log.close()


def test_uploader_lifecycle_and_idempotent_pass(tmp_path):
    """TierUploader drives Broker.run_tiering; a second pass over an
    unchanged log is a no-op (manifest entries match byte-for-byte)."""
    broker = Broker(store_dir=str(tmp_path / "store"),
                    store_policy=StorePolicy(fsync="never",
                                             segment_bytes=1024),
                    tier=TierPolicy(uri=str(tmp_path / "bucket")))
    broker.create_topic("T", partitions=1)
    for i in range(60):
        broker.produce("T", b"v%d" % i, timestamp_ms=i)
    broker.store.log_for("T", 0).roll()
    up = TierUploader(broker, interval_s=3600.0)
    out = up.run_once()
    assert out and all(s["uploaded"] >= 1 for s in out.values())
    assert up.run_once() == {}  # idempotent: nothing changed
    up.start()
    assert up._thread is not None and up._thread.name == \
        "iotml-tier-uploader"
    up.stop()
    assert up._thread is None
    broker.close()
    # untiered broker: run_tiering is a cheap no-op
    plain = Broker(store_dir=str(tmp_path / "plain"),
                   store_policy=StorePolicy(fsync="never"))
    assert TierUploader(plain).run_once() == {}
    plain.close()


def test_tier_config_env_keys(monkeypatch):
    """IOTML_TIER_* env keys resolve into the tier.* config section
    (first-underscore partition rule; D1 drift-checks the full set)."""
    from iotml.config import load_config

    monkeypatch.setenv("IOTML_TIER_URI", "/data/tier")
    monkeypatch.setenv("IOTML_TIER_LOCAL_HOT_BYTES", "4096")
    monkeypatch.setenv("IOTML_TIER_UPLOAD_LAG_S", "2.5")
    monkeypatch.setenv("IOTML_TIER_REMOTE_RETENTION_MS", "604800000")
    cfg, _ = load_config([])
    assert cfg.tier.uri == "/data/tier"
    assert cfg.tier.local_hot_bytes == 4096
    assert cfg.tier.upload_lag_s == 2.5
    assert cfg.tier.remote_retention_ms == 604800000
    pol = TierPolicy.from_config(cfg.tier)
    assert bool(pol) and pol.uri == "/data/tier"
    assert not TierPolicy()  # no uri -> tiering off


def test_manifest_is_the_commit_marker(tmp_path):
    """Protocol shape on the wire: the manifest JSON lists exactly the
    committed segments with size+CRC, and sweep() removes everything
    else under the prefix."""
    log, remote, store = _tiered(tmp_path)
    _fill(log, 100)
    log.roll()
    log.tier_sync()
    doc = json.loads(store.get_text("tiered/T/0/manifest.json"))
    assert {e["base"] for e in doc["segments"]} == \
        {m.base for m in log.remote_metas()}
    for e in doc["segments"]:
        assert e["size"] > 0 and e["crc"] >= 0 and e["next"] > e["base"]
    # a foreign unreferenced blob is swept
    store.put_text("tiered/T/0/99999999999999999999.stage", "{}")
    assert remote.sweep() == 1
    assert remote.sweep() == 0
    log.close()
