"""Record-level tracing (iotml.obs.tracing): context propagation device
→ MQTT → bridge → KSQL → consumer → scorer/train via record headers,
the lock-free span collector, the Prometheus/JSONL/healthz exporters and
the ``python -m iotml.obs trace`` CLI.

The acceptance pipeline (ISSUE 2): a traced local run produces a span
log with >= 5 distinct stages, the CLI prints a per-stage breakdown and
flags the bottleneck, and the stage/e2e histograms render valid
exposition text — while the DISABLED default records nothing and
allocates nothing on the record path.
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.mqtt.bridge import KafkaBridge
from iotml.mqtt.broker import MqttBroker
from iotml.obs import metrics as obs_metrics
from iotml.obs import tracing
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.streamproc.tasks import JsonToAvro, RekeyByCar


@pytest.fixture
def traced(tmp_path):
    """Tracing on, full sampling, span log in tmp; restored after."""
    path = str(tmp_path / "spans.jsonl")
    tracing.reset()
    tracing.configure(enabled=True, sample=1.0, path=path)
    try:
        yield path
    finally:
        tracing.configure(enabled=False, sample=1.0)
        tracing.reset()
        tracing._PATH = None


def _sensor_json(i: int) -> bytes:
    rec = {"coolant_temp": 20.0 + i, "intake_air_temp": 21.0,
           "intake_air_flow_speed": 1.0, "battery_percentage": 70.0,
           "battery_voltage": 220.0, "current_draw": 5.0, "speed": 20.0,
           "engine_vibration_amplitude": 2000.0, "throttle_pos": 0.4,
           "tire_pressure_1_1": 30, "tire_pressure_1_2": 30,
           "tire_pressure_2_1": 30, "tire_pressure_2_2": 30,
           "accelerometer_1_1_value": 0.5, "accelerometer_1_2_value": 0.5,
           "accelerometer_2_1_value": 0.5, "accelerometer_2_2_value": 0.5,
           "control_unit_firmware": 1000, "failure_occurred": "false"}
    return json.dumps(rec).encode()


def _mqtt_to_avro_pipeline(n=30):
    """devsim-shaped publishes → MQTT broker → bridge → KSQL tasks."""
    mqtt = MqttBroker()
    stream = Broker()
    KafkaBridge(mqtt, stream, partitions=2)
    for i in range(n):
        mqtt.publish(f"vehicles/sensor/data/car{i % 5}", _sensor_json(i),
                     qos=1)
    JsonToAvro(stream, src="sensor-data",
               dst="SENSOR_DATA_S_AVRO").process_available()
    RekeyByCar(stream, src="SENSOR_DATA_S_AVRO",
               dst="SENSOR_DATA_S_AVRO_REKEY",
               partitions=2).process_available()
    return stream


# ------------------------------------------------------------- unit level
def test_context_marks_and_closes_spans(traced):
    ctx = tracing.start("mqtt_publish")
    assert ctx is not None
    ctx.mark("consume")
    ctx.close("score")
    ctx.close("score")  # idempotent: double close records nothing new
    assert tracing.flush() == {"spans": 3, "e2e": 1}
    rows = [json.loads(l) for l in open(traced)]
    stages = [r["stage"] for r in rows if r["kind"] == "span"]
    assert stages == ["mqtt_publish", "consume", "score"]
    e2e = [r for r in rows if r["kind"] == "e2e"]
    assert len(e2e) == 1 and e2e[0]["closer"] == "score"
    # one trace id threads every row
    assert len({r["trace"] for r in rows}) == 1


def test_disabled_records_nothing_and_attaches_no_headers():
    tracing.reset()
    assert tracing.ENABLED is False  # the off-by-default contract
    assert tracing.start("mqtt_publish") is None
    assert tracing.headers_for(None) is None
    broker = Broker()
    broker.produce("t", b"v")
    assert broker.fetch("t", 0, 0)[0].headers is None
    assert tracing.flush() == {"spans": 0, "e2e": 0}


def test_sampling_zero_traces_nothing(traced):
    tracing.configure(sample=0.0)
    try:
        assert tracing.start("mqtt_publish") is None
    finally:
        tracing.configure(sample=1.0)


def test_wire_encode_decode_roundtrip(traced):
    ctx = tracing.start("mqtt_publish")
    raw = ctx.encode()
    back = tracing.TraceContext.decode(raw)
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.wall0_ns == ctx.wall0_ns
    assert tracing.TraceContext.decode(b"junk") is None
    # headers carry either the live object or the byte form
    assert tracing.from_headers(((tracing.HEADER_KEY, ctx),)) is ctx
    assert tracing.from_headers(((tracing.HEADER_KEY, raw),)).trace_id \
        == ctx.trace_id
    assert tracing.from_headers(None) is None


def test_broker_carries_headers_through_produce_and_fetch():
    broker = Broker()
    broker.create_topic("t", partitions=2)
    hdr = (("iotml_trace", "x"),)
    broker.produce("t", b"v1", key=b"k", headers=hdr)
    broker.produce_many("t", [(b"k", b"v2", 0, hdr), (b"k", b"v3", 0)])
    msgs = []
    for p in range(2):
        msgs += broker.fetch("t", p, 0)
    by_val = {m.value: m.headers for m in msgs}
    assert by_val[b"v1"] == hdr and by_val[b"v2"] == hdr
    assert by_val[b"v3"] is None


# ------------------------------------------------------- pipeline level
def _e2e_score_count() -> float:
    # the registry is process-global (accumulates across tests): count
    # deltas, never absolutes
    return obs_metrics.default_registry.collect().get(
        "iotml_e2e_ingest_to_score_seconds_count", 0.0)


def test_trace_propagates_mqtt_to_scorer_stages(traced):
    before = _e2e_score_count()
    stream = _mqtt_to_avro_pipeline(n=30)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"], group="g")
    batches = SensorBatches(consumer, batch_size=10)
    assert sum(b.n_valid for b in batches) == 30
    for ctx in batches.take_traces():
        ctx.close("score")
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)]
    stages = {r["stage"] for r in rows if r["kind"] == "span"}
    # the acceptance bar: >= 5 distinct stages through the real pipeline
    assert {"mqtt_publish", "mqtt_deliver", "bridge_produce",
            "streamproc", "consume", "score"} <= stages
    e2e = [r for r in rows if r["kind"] == "e2e"]
    assert len(e2e) == 30
    assert all(r["dur_us"] > 0 for r in e2e)
    # histograms made it into the registry with valid exposition
    text = obs_metrics.default_registry.render()
    assert 'iotml_stage_seconds_count{stage="consume"}' in text
    assert _e2e_score_count() - before == 30


def test_scorer_closes_traces_end_to_end(traced):
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    before = _e2e_score_count()
    stream = _mqtt_to_avro_pipeline(n=30)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="serve")
    batches = SensorBatches(consumer, batch_size=10)
    trainer = Trainer(CAR_AUTOENCODER)
    trainer._ensure_state(np.zeros((10, 18), np.float32))
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params, batches,
                          OutputSequence(stream, "model-predictions",
                                         partition=0))
    assert scorer.score_available() == 30
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)]
    closers = [r["closer"] for r in rows if r["kind"] == "e2e"]
    assert closers.count("score") == 30
    assert _e2e_score_count() - before == 30


def test_trainer_closes_traces_with_train_e2e(traced):
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.train.loop import Trainer

    gen = FleetGenerator(FleetScenario(num_cars=20, seed=3))
    stream = Broker()
    gen.publish(stream, "SENSOR_DATA_S_AVRO", n_ticks=3, partitions=1)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="train")
    batches = SensorBatches(consumer, batch_size=10, only_normal=True)
    Trainer(CAR_AUTOENCODER).fit(batches, epochs=1)
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)]
    stages = {r["stage"] for r in rows if r["kind"] == "span"}
    assert "devsim_publish" in stages and "train" in stages
    assert any(r["kind"] == "e2e" and r["closer"] == "train" for r in rows)


def test_two_pipelines_close_their_own_forks(traced):
    """The demo's normal shape — train over a topic, then score the SAME
    topic with another consumer group.  The header carries one shared
    context; each pipeline must fork and close its own copy, or the
    first closer steals the trace and the scorer leg goes dark
    (regression: pre-fork, zero 'score' e2e spans came out of the demo).
    Epoch re-reads within ONE pipeline still trace once (dedup)."""
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.train.loop import Trainer

    stream = _mqtt_to_avro_pipeline(n=30)
    # pipeline 1: train, 2 epochs (the epoch re-read must not re-close)
    c1 = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"], group="train")
    b1 = SensorBatches(c1, batch_size=10, only_normal=True)
    Trainer(CAR_AUTOENCODER).fit(b1, epochs=2)
    # pipeline 2: an independent consumer group over the same topic
    c2 = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"], group="serve")
    b2 = SensorBatches(c2, batch_size=10)
    assert sum(b.n_valid for b in b2) == 30
    for ctx in b2.take_traces():
        ctx.close("score")
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)]
    closers = [r["closer"] for r in rows if r["kind"] == "e2e"]
    assert closers.count("train") == 30  # once, not once per epoch
    assert closers.count("score") == 30  # NOT stolen by the train close
    # both pipelines logged under the same trace ids (one id, two closers)
    by_kind = {}
    for r in rows:
        if r["kind"] == "e2e":
            by_kind.setdefault(r["trace"], set()).add(r["closer"])
    assert all(v == {"train", "score"} for v in by_kind.values())


def test_truncated_drain_defers_close_until_complete(traced):
    """A max_rows-truncated drain must NOT close traces — rows are still
    buffered in the suspended iterator; the completing drain closes all."""
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    stream = _mqtt_to_avro_pipeline(n=30)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="serve")
    batches = SensorBatches(consumer, batch_size=10)
    trainer = Trainer(CAR_AUTOENCODER)
    trainer._ensure_state(np.zeros((10, 18), np.float32))
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params, batches,
                          OutputSequence(stream, "model-predictions",
                                         partition=0))
    assert scorer.score_available(max_rows=10) >= 10
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)] if os.path.exists(traced) \
        else []
    assert not any(r["kind"] == "e2e" for r in rows)
    scorer.score_available()  # completes the drain
    tracing.flush()
    rows = [json.loads(l) for l in open(traced)]
    assert sum(r["kind"] == "e2e" for r in rows) == 30


def test_large_drain_holds_every_pending_fork(traced):
    """Regression: the pending-forks bound must cover a full drain at
    full sampling — a 4096-cap silently dropped ~900 of a 5000-record
    backlog's e2e spans before the closer ever saw them."""
    gen = FleetGenerator(FleetScenario(num_cars=100, seed=5))
    stream = Broker()
    gen.publish(stream, "SENSOR_DATA_S_AVRO", n_ticks=50, partitions=1)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="big")
    batches = SensorBatches(consumer, batch_size=100)
    assert sum(b.n_valid for b in batches) == 5000
    forks = batches.take_traces()
    assert len(forks) == 5000
    for ctx in forks:
        ctx.close("score")
    assert tracing.flush()["e2e"] == 5000


def test_collector_is_lock_free_under_lockcheck():
    """Recording a span takes no lock: under the runtime lockcheck the
    record path must not create or acquire any CheckedLock (the R6 lint
    closes the same invariant statically)."""
    from iotml.analysis import lockcheck

    if lockcheck.state() is not None:
        pytest.skip("session-level lockcheck active")
    tracing.reset()
    tracing.configure(enabled=True, sample=1.0)
    st = lockcheck.install()
    try:
        ctx = tracing.start("mqtt_publish")
        ctx.mark("consume")
        ctx.close("score")
        assert st.cycles() == []
        assert not any(v.kind == "io-under-lock" for v in st.violations)
    finally:
        lockcheck.uninstall()
        tracing.configure(enabled=False)
        tracing.reset()


def test_liveness_reports_stage_ages(traced):
    ctx = tracing.start("mqtt_publish")
    ctx.close("score")
    ages = tracing.liveness()
    assert set(ages) >= {"mqtt_publish", "score"}
    assert all(a >= 0 for a in ages.values())


def test_healthz_endpoint_serves_stage_liveness(traced):
    ctx = tracing.start("mqtt_publish")
    ctx.close("score")
    srv = obs_metrics.start_http_server(port=0)
    try:
        port = srv.server_address[1]
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        assert doc["status"] == "ok" and doc["tracing"] is True
        assert "mqtt_publish" in doc["stages"]
        assert doc["stages"]["score"]["last_span_age_s"] >= 0
        # the scrape path drains spans into the histograms too
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert 'iotml_stage_seconds_count{stage="mqtt_publish"}' in body
    finally:
        srv.shutdown()


def test_env_configuration(monkeypatch, tmp_path):
    monkeypatch.setenv("IOTML_TRACE", "1")
    monkeypatch.setenv("IOTML_TRACE_SAMPLE", "0.25")
    monkeypatch.setenv("IOTML_TRACE_PATH", str(tmp_path / "t.jsonl"))
    tracing.configure_from_env()
    try:
        assert tracing.ENABLED is True
        assert tracing._SAMPLE == 0.25
        assert tracing._PATH == str(tmp_path / "t.jsonl")
    finally:
        tracing.configure(enabled=False, sample=1.0)
        tracing._PATH = None
    # the toggles are process toggles, not pipeline config: the loud
    # failure typo check must accept them
    from iotml.config import load_config

    cfg, _ = load_config(env={"IOTML_TRACE": "1",
                              "IOTML_TRACE_SAMPLE": "0.01",
                              "IOTML_TRACE_PATH": "/tmp/x.jsonl"})
    assert cfg.train.epochs == 20  # resolved fine, toggles ignored


# ------------------------------------------------------------------- CLI
def test_obs_trace_cli_summarizes_and_flags_bottleneck(traced, tmp_path):
    stream = _mqtt_to_avro_pipeline(n=30)
    consumer = StreamConsumer(stream, ["SENSOR_DATA_S_AVRO:0:0"], group="g")
    batches = SensorBatches(consumer, batch_size=10)
    list(batches)
    for ctx in batches.take_traces():
        ctx.close("score")
    tracing.flush()
    proc = subprocess.run(
        [sys.executable, "-m", "iotml.obs", "trace", traced,
         "--min-stages", "5", "--require-e2e"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bottleneck:" in proc.stdout
    for stage in ("mqtt_publish", "streamproc", "consume", "score"):
        assert stage in proc.stdout
    assert "e2e ingest->score" in proc.stdout
    # --json emits the machine-readable summary
    proc = subprocess.run(
        [sys.executable, "-m", "iotml.obs", "trace", traced, "--json"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    doc = json.loads(proc.stdout)
    assert doc["bottleneck"] in {s["stage"] for s in doc["stages"]}
    assert doc["e2e"]["score"]["count"] == 30


def test_obs_trace_cli_check_failure_exit_code(tmp_path):
    path = tmp_path / "sparse.jsonl"
    path.write_text(json.dumps(
        {"kind": "span", "trace": "00", "stage": "consume",
         "start_us": 0, "dur_us": 5, "wall0_ns": 0}) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "iotml.obs", "trace", str(path),
         "--min-stages", "5", "--require-e2e"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    assert "TRACE CHECK FAILED" in proc.stderr
