"""End-to-end slice: generator → broker → decode → normalize → train →
checkpoint → score → ordered write-back.  This is SURVEY §7 stage 4 — the
reference's full train/predict call stacks (§3.1, §3.2) against the
in-process broker."""

import jax
import numpy as np

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.models.autoencoder import CAR_AUTOENCODER
from iotml.models.lstm import LSTMSeq2Seq
from iotml.serve.scorer import StreamScorer
from iotml.stream.broker import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.stream.producer import OutputSequence
from iotml.train.checkpoint import CheckpointManager
from iotml.train.loop import Trainer


def build_world(num_cars=50, ticks=10, failure_rate=0.05):
    broker = Broker()
    broker.create_topic("model-predictions")
    gen = FleetGenerator(FleetScenario(num_cars=num_cars, failure_rate=failure_rate))
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=ticks)
    return broker, gen


def test_autoencoder_train_loss_decreases():
    broker, _ = build_world()
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    batches = SensorBatches(consumer, batch_size=100, only_normal=True)
    trainer = Trainer(CAR_AUTOENCODER)
    hist = trainer.fit(batches, epochs=5)
    assert len(hist["loss"]) == 5
    assert hist["loss"][-1] < hist["loss"][0]
    assert hist["records"][0] > 0
    # every epoch re-read the same records (streaming re-read semantics)
    assert len(set(hist["records"])) == 1


def test_train_then_score_roundtrip():
    broker, _ = build_world(num_cars=40, ticks=10)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(SensorBatches(consumer, batch_size=50, only_normal=True), epochs=2)

    # predict over everything (reference predict path: no filter)
    consumer2 = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    pred_batches = SensorBatches(consumer2, batch_size=50)
    out = OutputSequence(broker, "model-predictions", partition=0)
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params, pred_batches, out)
    n = scorer.score_available()
    assert n == 400
    msgs = broker.fetch("model-predictions", 0, 0, 1000)
    assert len(msgs) == 400
    # reference payload format: np.array2string of the output row
    assert msgs[0].value.startswith(b"[")


def test_scorer_incremental_drains_keep_order():
    broker, gen = build_world(num_cars=20, ticks=5)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"], eof=True)
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(SensorBatches(StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"]),
                              batch_size=50, only_normal=True), epochs=1)
    out = OutputSequence(broker, "model-predictions", partition=0)
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params,
                          SensorBatches(consumer, batch_size=50), out)
    n1 = scorer.score_available()
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=3)  # more data arrives
    n2 = scorer.score_available()
    assert n1 == 100 and n2 == 60
    assert len(broker.fetch("model-predictions", 0, 0, 1000)) == 160


def test_checkpoint_resume_cursors_and_params(tmp_path):
    broker, _ = build_world(num_cars=30, ticks=5)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"], group="train")
    batches = SensorBatches(consumer, batch_size=50, only_normal=True)
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(batches, epochs=1)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(trainer.state, cursors=consumer.positions())
    restored = mgr.restore()
    assert restored["step"] == int(trainer.state.step)
    assert restored["cursors"][0][0] == "SENSOR_DATA_S_AVRO"
    assert restored["cursors"][0][2] == consumer.positions()[0][2]
    for a, b in zip(jax.tree.leaves(restored["params"]),
                    jax.tree.leaves(jax.device_get(trainer.state.params))):
        np.testing.assert_array_equal(a, b)


def test_lstm_supervised_training_runs():
    broker, _ = build_world(num_cars=10, ticks=40, failure_rate=0.0)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    batches = SensorBatches(consumer, batch_size=16, window=1)
    trainer = Trainer(LSTMSeq2Seq(features=18, look_back=1), supervised=True)
    hist = trainer.fit(batches, epochs=2)
    assert len(hist["loss"]) == 2
    assert np.isfinite(hist["loss"]).all()


def test_scorer_deep_backlog_bounded_super_batches():
    """ADVICE r1: a drain deeper than max_super_batches proceeds in bounded
    chunks — every row still scored exactly once, ordering preserved."""
    broker, _ = build_world(num_cars=40, ticks=10)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(SensorBatches(consumer, batch_size=50, only_normal=True), epochs=1)

    consumer2 = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    pred_batches = SensorBatches(consumer2, batch_size=50)
    out = OutputSequence(broker, "model-predictions", partition=0)
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params, pred_batches, out)
    scorer.max_super_batches = 2  # 400 rows / 50 per batch = 8 batches -> 4 chunks
    n = scorer.score_available()
    assert n == 400
    msgs = broker.fetch("model-predictions", 0, 0, 1000)
    assert len(msgs) == 400
    assert all(m.value.startswith(b"[") for m in msgs)
