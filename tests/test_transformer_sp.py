"""SensorFormer + sequence-parallel training on the virtual 8-device mesh:
the sharded path must match the single-device dense oracle exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from iotml.models.transformer import SensorFormer
from iotml.parallel.mesh import make_mesh
from iotml.parallel.seq_parallel import (make_sp_train_step,
                                         sp_next_step_loss_reference)


def _x(B=4, T=32, F=18, seed=0):
    return np.random.default_rng(seed).normal(size=(B, T, F)).astype(np.float32)


def test_sensorformer_forward_shapes():
    m = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=2)
    x = jnp.asarray(_x())
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    out = m.apply({"params": params}, x)
    assert out.shape == x.shape
    scores = SensorFormer.anomaly_scores(out, x)
    assert scores.shape == (4, 31)


def test_sensorformer_flash_interpret_matches_dense():
    dense = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=1)
    flash = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=1,
                         attn_mode="flash_interpret")
    x = jnp.asarray(_x(T=40))
    params = dense.init(jax.random.PRNGKey(1), x)["params"]
    np.testing.assert_allclose(
        np.asarray(dense.apply({"params": params}, x)),
        np.asarray(flash.apply({"params": params}, x)),
        rtol=2e-4, atol=2e-4)


def test_sp_train_step_matches_dense_oracle():
    mesh = make_mesh((2, 4), ("data", "seq"))
    model = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=2,
                         attn_mode="ring", ring_axis="seq")
    tx = optax.adam(1e-3)
    init, step, put_x = make_sp_train_step(model, tx, mesh)

    x = _x(B=4, T=32)
    state = init(jax.random.PRNGKey(0), x)
    params0 = jax.device_get(state.params)

    # oracle loss with the same params, dense attention, single device
    dense = model.clone(attn_mode="dense")
    want = float(sp_next_step_loss_reference(dense, params0, jnp.asarray(x)))

    state, metrics = step(state, put_x(x))
    got = float(metrics["loss"])
    assert got == pytest.approx(want, rel=1e-5)

    # gradients flowed: params changed, loss drops over a few steps
    losses = [got]
    for _ in range(5):
        state, metrics = step(state, put_x(x))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_sp_gradients_match_dense_oracle():
    """With SGD the param delta is -lr*grad, so comparing post-step params
    compares the sharded gradients themselves against the dense oracle's.
    (Adam's first step is -lr*sign(g) — scale-free — which would amplify
    float noise in near-zero grads into full-size deltas.)"""
    mesh = make_mesh((2, 4), ("data", "seq"))
    model = SensorFormer(features=18, d_model=32, num_heads=2, num_layers=1,
                         attn_mode="ring", ring_axis="seq")
    tx = optax.sgd(0.1)
    init, step, put_x = make_sp_train_step(model, tx, mesh)
    x = _x(B=4, T=32, seed=5)
    state = init(jax.random.PRNGKey(2), x)
    params0 = jax.device_get(state.params)

    dense = model.clone(attn_mode="dense")
    ref_grads = jax.grad(
        lambda p: sp_next_step_loss_reference(dense, p, jnp.asarray(x)))(params0)
    want = jax.tree.map(lambda p, g: p - 0.1 * np.asarray(g),
                        params0, jax.device_get(ref_grads))
    state, _ = step(state, put_x(x))
    got = jax.device_get(state.params)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
