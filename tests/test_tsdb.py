"""Log-native TSDB + burn-rate SLO engine + canary plane (ISSUE 17).

Covers the telemetry-plane contracts: delta-encoded chunk append →
replay round trip, the PromQL-subset query engine (instant/range,
matchers, reset-corrected ``rate()``, ``histogram_quantile``), the
counter-reset regression under a REAL supervised restart, TSDB
boundedness under forced compaction, the incremental ``TsdbTail``
reader, SLO fire→resolve transitions on the ``_IOTML_ALERTS``
changelog, the canary firewall (reserved ids never reach scoring), the
trace-sourced canary e2e through the real MQTT→bridge→converter path,
the ``/query`` REST surface, and the ``parse_prom_text`` round trip.
"""

import http.client
import json
import math
import threading
import time
import urllib.parse

import numpy as np
import pytest

from iotml.data.dataset import SensorBatches
from iotml.gen.simulator import FleetGenerator, FleetScenario
from iotml.obs import canary as canary_mod
from iotml.obs import federate, slo as slo_mod, tracing, tsdb
from iotml.obs import metrics as metrics_mod
from iotml.stream import Broker
from iotml.stream.consumer import StreamConsumer
from iotml.supervise.supervisor import Supervisor

BASE_TS = 1_700_000_000_000  # fixed event-time origin for all samples


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset()
    yield
    tracing.configure(enabled=False, sample=1.0, path="")
    tracing.reset()


def _count_records(broker, topic, partition=0):
    n = 0
    off = broker.begin_offset(topic, partition)
    end = broker.end_offset(topic, partition)
    while off < end:
        batch = broker.fetch(topic, partition, off, 4096)
        if not batch:
            break
        for m in batch:
            off = m.offset + 1
            n += 1
    return n


# ------------------------------------------------------------ appender
def test_appender_roundtrip_delta_encoding():
    broker = Broker()
    app = tsdb.TsdbAppender(broker, chunk_ms=1_000)
    for i in range(25):  # 100 ms cadence across 3 chunk windows
        app.append([("iotml_rt_total", {"job": "a"}, float(i)),
                    ("iotml_rt_gauge", {}, float(i % 4))],
                   ts_ms=BASE_TS + i * 100)

    # the wire chunks really are delta-encoded: t[0] absolute, the rest
    # the (small) scrape-cadence deltas
    raw = broker.fetch(tsdb.TSDB_TOPIC, 0, 0, 4)
    doc = json.loads(raw[0].value)
    assert doc["t"][0] >= BASE_TS
    assert all(d == 100 for d in doc["t"][1:])

    series = tsdb.read_series(broker)
    sid = tsdb.series_id("iotml_rt_total", {"job": "a"})
    got = series[sid]["samples"]
    assert got == [(BASE_TS + i * 100, float(i)) for i in range(25)]
    assert series[sid]["l"] == {"job": "a"}
    # re-appending a window keeps the newest (most complete) copy only
    assert len(tsdb.read_series(broker)[sid]["samples"]) == 25


def test_appender_dedup_and_ordering_rules():
    broker = Broker()
    app = tsdb.TsdbAppender(broker, chunk_ms=60_000)
    app.append([("m", {}, 1.0)], ts_ms=BASE_TS)
    app.append([("m", {}, 9.0)], ts_ms=BASE_TS)        # same stamp: LWW
    app.append([("m", {}, 5.0)], ts_ms=BASE_TS - 10)   # out of order: drop
    app.append([("m", {}, 2.0)], ts_ms=BASE_TS + 500)
    samples = tsdb.read_series(broker)[tsdb.series_id("m", {})]["samples"]
    assert samples == [(BASE_TS, 9.0), (BASE_TS + 500, 2.0)]

    # process relabel applied at write time
    app.append([("m", {}, 3.0)], ts_ms=BASE_TS + 600, process="scorer")
    sid = tsdb.series_id("m", {"process": "scorer"})
    assert tsdb.read_series(broker)[sid]["l"] == {"process": "scorer"}


# ------------------------------------------------------------- queries
def _mkseries(points):
    """points: {(name, labels-tuple): [(ts, v)...]} → series dict."""
    out = {}
    for (name, labels), samples in points.items():
        labels = dict(labels)
        out[tsdb.series_id(name, labels)] = {
            "n": name, "l": labels, "samples": sorted(samples)}
    return out


def test_instant_and_range_with_matchers():
    series = _mkseries({
        ("up", (("job", "scorer"),)): [(BASE_TS + i * 1_000, 1.0)
                                       for i in range(10)],
        ("up", (("job", "trainer"),)): [(BASE_TS + i * 1_000, 0.0)
                                        for i in range(10)],
    })
    at = BASE_TS + 9_000
    allr = tsdb.instant(series, "up", at_ms=at)
    assert {tuple(r["labels"].items()) for r in allr} == {
        (("job", "scorer"),), (("job", "trainer"),)}

    eq = tsdb.instant(series, "up", [tsdb.Matcher("job", "=", "scorer")],
                      at_ms=at)
    assert len(eq) == 1 and eq[0]["value"] == 1.0
    ne = tsdb.instant(series, "up", [tsdb.Matcher("job", "!=", "scorer")],
                      at_ms=at)
    assert len(ne) == 1 and ne[0]["labels"]["job"] == "trainer"
    rex = tsdb.instant(series, "up", [tsdb.Matcher("job", "=~", "sc.*")],
                       at_ms=at)
    assert len(rex) == 1 and rex[0]["labels"]["job"] == "scorer"
    nrex = tsdb.instant(series, "up", [tsdb.Matcher("job", "!~", "sc.*")],
                        at_ms=at)
    assert len(nrex) == 1 and nrex[0]["labels"]["job"] == "trainer"

    # staleness: an instant past the lookback answers nothing
    assert tsdb.instant(series, "up", at_ms=at + 400_000) == []

    # range: last-observed carry at every step, staleness-bounded
    rq = tsdb.range_query(series, "up",
                          [tsdb.Matcher("job", "=", "scorer")],
                          start_ms=BASE_TS, end_ms=BASE_TS + 20_000,
                          step_ms=5_000)
    assert len(rq) == 1
    assert rq[0]["values"] == [(BASE_TS + k * 5_000, 1.0)
                               for k in range(5)]


def test_parse_selector_and_query_expressions():
    name, matchers, window = tsdb.parse_selector(
        'iotml_x_total{job="a",mode=~"b.*"}[5m]')
    assert name == "iotml_x_total" and window == 300_000
    assert [(m.key, m.op, m.value) for m in matchers] == [
        ("job", "=", "a"), ("mode", "=~", "b.*")]
    with pytest.raises(ValueError):
        tsdb.parse_selector("{nometric}")
    with pytest.raises(ValueError):
        tsdb.parse_duration_ms("5x")

    series = _mkseries({
        ("c_total", ()): [(BASE_TS + i * 1_000, float(10 * i))
                          for i in range(30)]})
    at = BASE_TS + 29_000
    r = tsdb.query(series, "rate(c_total[30s])", at_ms=at)
    assert len(r) == 1 and r[0]["value"] == pytest.approx(10.0)
    inc = tsdb.query(series, "increase(c_total[10s])", at_ms=at)
    assert inc[0]["value"] == pytest.approx(100.0)
    with pytest.raises(ValueError):
        tsdb.query(series, "rate(c_total)")         # needs a [window]
    with pytest.raises(ValueError):
        tsdb.query(series, "c_total[5m]")           # bare selector + window
    ranged = tsdb.query(series, "rate(c_total[10s])",
                        start_ms=BASE_TS + 15_000, end_ms=at,
                        step_ms=7_000)
    assert ranged and all(v == pytest.approx(10.0)
                          for _, v in ranged[0]["values"])


# ------------------------------------------------- counter-reset rate()
def test_rate_counter_reset_never_negative():
    series = _mkseries({
        ("req_total", ()): [
            (BASE_TS, 100.0), (BASE_TS + 1_000, 150.0),
            (BASE_TS + 2_000, 200.0),
            (BASE_TS + 3_000, 5.0),    # restart: counter re-starts low
            (BASE_TS + 4_000, 55.0)]})
    before = tsdb.tsdb_resets.value()
    r = tsdb.rate(series, "req_total", window_ms=60_000,
                  at_ms=BASE_TS + 4_000)
    assert len(r) == 1
    assert r[0]["value"] >= 0.0
    # increase = 50 + 50 + 5 (post-reset absolute) + 50 over 4 s
    assert r[0]["value"] == pytest.approx(155.0 / 4.0)
    assert r[0]["resets"] == 1
    assert tsdb.tsdb_resets.value() == before + 1


def test_supervised_restart_mid_scrape_rate_regression():
    """ISSUE 17 satellite (b): restart a supervised unit mid-scrape
    stream; the unit's process-local counter re-starts from zero, and
    ``rate()`` over the stored samples must read that as a reset
    (counted in iotml_tsdb_resets_total), never as a negative rate."""
    broker = Broker()
    app = tsdb.TsdbAppender(broker, chunk_ms=3_600_000)
    tick = {"i": 0}
    crashed = threading.Event()
    finished = threading.Event()

    def scrape_loop(unit):
        count = 0.0  # process-local: the restart re-creates it at zero
        while not unit.should_stop():
            count += 10.0
            i = tick["i"]
            tick["i"] += 1
            app.append([("iotml_unit_work_total", {"unit": "w"}, count)],
                       ts_ms=BASE_TS + i * 1_000)
            unit.heartbeat()
            if not crashed.is_set() and count >= 50.0:
                crashed.set()
                raise RuntimeError("simulated crash mid-scrape")
            if crashed.is_set() and count >= 30.0:
                finished.set()
                while not unit.should_stop():
                    time.sleep(0.01)
                return
            time.sleep(0.005)

    before = tsdb.tsdb_resets.value()
    sup = Supervisor(poll_interval_s=0.02, name="tsdb-reset-test")
    unit = sup.add_loop("scraper", scrape_loop, heartbeat_timeout_s=30.0)
    sup.start()
    try:
        assert finished.wait(10.0), "supervised unit never recovered"
    finally:
        sup.stop()
    assert unit.restarts == 1
    assert "simulated crash" in (unit.last_error or "")

    series = tsdb.read_series(broker)
    r = tsdb.rate(series, "iotml_unit_work_total", window_ms=3_600_000)
    assert len(r) == 1
    assert r[0]["value"] >= 0.0, "rate went negative across a restart"
    assert r[0]["resets"] == 1
    assert tsdb.tsdb_resets.value() == before + 1
    # every evaluation point across the restart boundary stays >= 0
    for i in range(1, tick["i"]):
        for p in tsdb.rate(series, "iotml_unit_work_total",
                           window_ms=3_600_000,
                           at_ms=BASE_TS + i * 1_000):
            assert p["value"] >= 0.0


# ---------------------------------------------------- histogram_quantile
def _bucket_width(buckets, value):
    prev = 0.0
    for b in buckets:
        if value <= b:
            return b - prev
        prev = b
    return float("inf")


def _quantile_via_tsdb(values, buckets, q):
    """Render a real Histogram, parse the exposition, append the parsed
    samples into the TSDB, read back, interpolate — the whole path the
    federated scrape exercises."""
    reg = metrics_mod.Registry()
    h = reg.histogram("iotml_q_seconds", buckets=buckets)
    for v in values:
        h.observe(float(v))
    _types, samples = federate.parse_prom_text(reg.render())
    broker = Broker()
    tsdb.TsdbAppender(broker, chunk_ms=60_000).append(samples,
                                                      ts_ms=BASE_TS)
    series = tsdb.read_series(broker)
    out = tsdb.histogram_quantile(series, q, "iotml_q_seconds",
                                  at_ms=BASE_TS)
    assert len(out) == 1
    return out[0]["value"]


def test_histogram_quantile_uniform_within_bucket_width():
    buckets = tuple(round(0.1 * k, 1) for k in range(1, 11))  # 0.1 .. 1.0
    rng = np.random.default_rng(42)
    values = rng.uniform(0.0, 1.0, size=4_000)
    for q in (0.1, 0.5, 0.9, 0.99):
        est = _quantile_via_tsdb(values, buckets, q)
        true = float(np.quantile(values, q))
        tol = _bucket_width(buckets, true)
        assert abs(est - true) <= tol, (q, est, true, tol)


def test_histogram_quantile_bimodal_within_bucket_width():
    # two separated modes: the winning bucket flips between them as q
    # crosses the mass split, and interpolation must stay inside it
    buckets = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
    rng = np.random.default_rng(7)
    low = rng.uniform(0.0, 0.05, size=3_000)    # healthy mode (60 %)
    high = rng.uniform(0.6, 0.9, size=2_000)    # degraded mode (40 %)
    values = np.concatenate([low, high])
    for q in (0.25, 0.5, 0.75, 0.95):
        est = _quantile_via_tsdb(values, buckets, q)
        true = float(np.quantile(values, q))
        tol = _bucket_width(buckets, true)
        assert abs(est - true) <= tol, (q, est, true, tol)
    # the modes really are resolved: p25 in the low cluster, p95 high
    assert _quantile_via_tsdb(values, buckets, 0.25) < 0.1
    assert _quantile_via_tsdb(values, buckets, 0.95) > 0.5


def test_histogram_quantile_rejects_bad_q():
    with pytest.raises(ValueError):
        tsdb.histogram_quantile({}, 1.5, "x")


# ------------------------------------------------- compaction boundedness
def test_tsdb_bounded_under_forced_compaction(tmp_path):
    broker = Broker(store_dir=str(tmp_path))
    app = tsdb.TsdbAppender(broker, chunk_ms=1_000)
    for i in range(200):  # 10 samples per window, 20 windows, 2 series
        app.append([("iotml_b_total", {}, float(i)),
                    ("iotml_b_gauge", {"k": "v"}, float(i % 7))],
                   ts_ms=BASE_TS + i * 100)
    pre = _count_records(broker, tsdb.TSDB_TOPIC)
    assert pre == 400  # every scrape re-appended its window's chunk
    before = tsdb.read_series(broker)

    broker.store.log_for(tsdb.TSDB_TOPIC, 0).roll()
    broker.run_compaction(force=True)

    post = _count_records(broker, tsdb.TSDB_TOPIC)
    assert post == 40  # one record per live (series, window) key
    # compaction kept the newest chunk copies: the replay is identical
    assert tsdb.read_series(broker) == before


# ----------------------------------------------------------- TsdbTail
def test_tsdb_tail_matches_read_series_and_is_incremental():
    broker = Broker()
    app = tsdb.TsdbAppender(broker, chunk_ms=1_000)
    for i in range(30):
        app.append([("a_total", {}, float(i)),
                    ("b_total", {"x": "1"}, float(2 * i))],
                   ts_ms=BASE_TS + i * 100)
    now = BASE_TS + 3_000
    tail = tsdb.TsdbTail(broker)
    assert tail.collect(now) == tsdb.read_series(broker)

    # incremental: only the new records are decoded, same answer
    for i in range(30, 60):
        app.append([("a_total", {}, float(i))], ts_ms=BASE_TS + i * 100)
    assert tail.collect(BASE_TS + 6_000) == tsdb.read_series(broker)

    # family filter: the tail skips everything the rules don't read
    only_a = tsdb.TsdbTail(broker, names={"a_total"}).collect(
        BASE_TS + 6_000)
    assert set(s["n"] for s in only_a.values()) == {"a_total"}

    # lookback: chunks whose newest sample aged out are pruned
    bounded = tsdb.TsdbTail(broker, lookback_ms=2_000)
    got = bounded.collect(BASE_TS + 6_000)
    for s in got.values():
        assert all(ts >= BASE_TS + 4_000 for ts, _v in s["samples"])


def test_tsdb_tail_empty_topic():
    broker = Broker()
    assert tsdb.TsdbTail(broker).collect(BASE_TS) == {}


# ----------------------------------------------------------- SLO engine
def _ratio_rule(**over):
    doc = {"name": "api-availability", "objective": 0.99,
           "indicator": {"kind": "ratio", "bad": "err_total",
                         "total": "req_total"},
           "windows": (("fast", 2_000, 6_000, 10.0),
                       ("slow", 4_000, 12_000, 5.0))}
    doc.update(over)
    return doc


def _ratio_series(err_rate, n_s=60, step_ms=1_000):
    """req at 10/s; errors at err_rate fraction of them, cumulative."""
    req, err = [], []
    total = bad = 0.0
    for i in range(n_s):
        total += 10.0
        bad += 10.0 * err_rate
        req.append((BASE_TS + i * step_ms, total))
        err.append((BASE_TS + i * step_ms, bad))
    return _mkseries({("req_total", ()): req, ("err_total", ()): err})


def test_slo_engine_fire_and_resolve_transitions():
    broker = Broker()
    engine = slo_mod.SloEngine(broker, [_ratio_rule()], interval_s=0.1)
    now = BASE_TS + 59_000

    # healthy: zero errors → no transition, burn 0
    assert engine.evaluate(series=_ratio_series(0.0), now_ms=now) == []
    assert not engine.states["api-availability"].firing

    # total outage: 100 % errors → burn = 1/0.01 = 100 on BOTH legs of
    # the fast pair → fire, transition lands on _IOTML_ALERTS
    trans = engine.evaluate(series=_ratio_series(1.0), now_ms=now)
    assert [t["action"] for t in trans] == ["fire"]
    st = engine.states["api-availability"]
    assert st.firing and st.window == "fast"
    assert st.burn["fast"] == pytest.approx(100.0)
    assert slo_mod.slo_burn_rate.value(
        slo="api-availability", window="fast") == pytest.approx(100.0)
    assert "api-availability" in slo_mod.firing_alerts()
    doc = slo_mod.read_alerts(broker)["api-availability"]
    assert doc["action"] == "fire" and doc["firing"] is True

    # still burning: no duplicate transition
    assert engine.evaluate(series=_ratio_series(1.0), now_ms=now) == []

    # recovery: errors stop → resolve transition, /healthz surface clears
    trans = engine.evaluate(series=_ratio_series(0.0), now_ms=now)
    assert [t["action"] for t in trans] == ["resolve"]
    assert not engine.states["api-availability"].firing
    assert "api-availability" not in slo_mod.firing_alerts()
    doc = slo_mod.read_alerts(broker)["api-availability"]
    assert doc["action"] == "resolve" and doc["firing"] is False


def test_slo_short_spike_alone_never_pages():
    """Multi-window discipline: the SHORT leg burning while the long
    window is still healthy must not fire (the workbook's defence
    against paging on a blip)."""
    broker = Broker()
    engine = slo_mod.SloEngine(broker, [_ratio_rule()], interval_s=0.1)
    # 60 s of clean traffic, then a 1 s error blip sized so the 2 s
    # short window burns (3/20 = 15x budget) while the 6 s long window
    # stays under threshold (3/60 = 5x budget < 10)
    req, err = [], []
    total = bad = 0.0
    for i in range(60):
        total += 10.0
        if i == 59:
            bad += 3.0
        req.append((BASE_TS + i * 1_000, total))
        err.append((BASE_TS + i * 1_000, bad))
    series = _mkseries({("req_total", ()): req, ("err_total", ()): err})
    assert engine.evaluate(series=series, now_ms=BASE_TS + 59_000) == []
    assert not engine.states["api-availability"].firing


def test_slo_no_traffic_is_no_burn():
    broker = Broker()
    engine = slo_mod.SloEngine(broker, [_ratio_rule()], interval_s=0.1)
    assert engine.evaluate(series={}, now_ms=BASE_TS) == []
    assert engine.states["api-availability"].burn["fast"] == 0.0


def test_slo_latency_indicator_over_buckets():
    rule = {"name": "lat", "objective": 0.9,
            "indicator": {"kind": "latency", "metric": "lat_seconds",
                          "threshold_s": 0.1},
            "windows": (("fast", 2_000, 6_000, 5.0),)}
    broker = Broker()
    engine = slo_mod.SloEngine(broker, [rule], interval_s=0.1)

    reg = metrics_mod.Registry()
    h = reg.histogram("lat_seconds", buckets=(0.05, 0.1, 0.5, 1.0))
    app = tsdb.TsdbAppender(broker, chunk_ms=60_000)
    # scrape 0: nothing yet; then every observation is slow (1.0 > 0.1)
    app.append(federate.parse_prom_text(reg.render())[1], ts_ms=BASE_TS)
    for _ in range(50):
        h.observe(1.0)
    app.append(federate.parse_prom_text(reg.render())[1],
               ts_ms=BASE_TS + 1_000)
    series = tsdb.read_series(broker)
    trans = engine.evaluate(series=series, now_ms=BASE_TS + 1_000)
    assert [t["action"] for t in trans] == ["fire"]
    # err = 1.0, budget = 0.1 → burn 10
    assert engine.states["lat"].burn["fast"] == pytest.approx(10.0)


def test_slo_rule_validation():
    with pytest.raises(ValueError):
        slo_mod.SloRule.from_dict({"objective": 0.9})          # no name
    with pytest.raises(ValueError):
        slo_mod.SloRule.from_dict(_ratio_rule(objective=1.5))
    with pytest.raises(ValueError):
        slo_mod.SloRule.from_dict(
            {"name": "x", "objective": 0.9,
             "indicator": {"kind": "bogus"}})
    r = slo_mod.SloRule.from_dict(_ratio_rule())
    assert r.error_budget == pytest.approx(0.01)


def test_slo_engine_indicator_families_bound_the_tail():
    broker = Broker()
    engine = slo_mod.SloEngine(
        broker, canary_mod.default_slo_rules(), interval_s=0.1)
    assert engine._indicator_families() == {
        "iotml_canary_e2e_seconds_bucket", "iotml_canary_probes_total"}


# ------------------------------------------------------------- canaries
def test_sensor_batches_firewall_excludes_canary_records():
    broker = Broker()
    gen = FleetGenerator(FleetScenario(num_cars=10, failure_rate=0.0,
                                       seed=3))
    n = gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=5)
    assert n == 50
    # canary records are schema-valid fleet bytes under a reserved key
    tmpl = broker.fetch("SENSOR_DATA_S_AVRO", 0, 0, 1)[0].value
    for seq in (1, 2, 3):
        broker.produce(
            "SENSOR_DATA_S_AVRO", tmpl,
            key=b"vehicles/sensor/data/canary-%08d" % seq)

    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="fw-armed", eof=True)
    armed = SensorBatches(
        consumer, batch_size=10, pad_tail=False,
        exclude_key_marker=canary_mod.CANARY_KEY_MARKER)
    assert sum(b.n_valid for b in armed) == 50
    assert armed.records_seen == 53  # it SAW the canaries, then dropped

    control = SensorBatches(
        StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                       group="fw-off", eof=True),
        batch_size=1, pad_tail=False)
    assert sum(b.n_valid for b in control) == 53


def test_canary_probe_e2e_through_real_path_is_trace_sourced():
    from iotml.mqtt.bridge import KafkaBridge
    from iotml.mqtt.broker import MqttBroker
    from iotml.streamproc.tasks import JsonToAvro

    tracing.configure(enabled=True, sample=1.0)
    tracing.reset()
    mqtt = MqttBroker()
    stream = Broker()
    KafkaBridge(mqtt, stream, partitions=1)
    task = JsonToAvro(stream, src="sensor-data",
                      dst="SENSOR_DATA_S_AVRO", partitions=1)
    probe = canary_mod.CanaryProbe(mqtt, stream,
                                   topic="SENSOR_DATA_S_AVRO",
                                   interval_s=0.05, timeout_s=2.0)
    for _ in range(3):
        probe.probe_once()
        task.process_available()
        probe.observe()
    rep = probe.report()
    assert rep["sent"] == 3 and rep["ok"] == 3 and rep["lost"] == 0
    # e2e came from the PR 2 trace span headers, not the fallback clock
    assert rep["trace_sourced"] == 3
    assert rep["inflight"] == 0
    assert 0.0 <= rep["last_e2e_s"] < 5.0


def test_canary_probe_times_out_lost_records():
    from iotml.mqtt.broker import MqttBroker

    mqtt = MqttBroker()  # NO bridge: published probes never arrive
    stream = Broker()
    probe = canary_mod.CanaryProbe(mqtt, stream,
                                   topic="SENSOR_DATA_S_AVRO",
                                   interval_s=0.05, timeout_s=0.05)
    probe.probe_once()
    time.sleep(0.1)
    probe.observe()
    rep = probe.report()
    assert rep["lost"] == 1 and rep["ok"] == 0 and rep["inflight"] == 0


def test_default_slo_rules_shape():
    rules = [slo_mod.SloRule.from_dict(d)
             for d in canary_mod.default_slo_rules(window_scale=0.5)]
    assert {r.name for r in rules} == {"canary-e2e-latency",
                                       "canary-delivery"}
    assert all(r.window_scale == 0.5 for r in rules)


# ---------------------------------------------------------- REST surface
def test_rest_query_and_query_range():
    from iotml.connect import ConnectServer, ConnectWorker

    broker = Broker()
    app = tsdb.TsdbAppender(broker, chunk_ms=60_000)
    for i in range(30):
        app.append([("iotml_http_total", {"job": "a"}, float(10 * i)),
                    ("iotml_http_total", {"job": "b"}, float(i))],
                   ts_ms=BASE_TS + i * 1_000)

    server = ConnectServer(ConnectWorker(broker),
                           poll_interval_s=9999).start()
    try:
        server.attach_tsdb(broker)
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=5)

        def get(path):
            conn.request("GET", path)
            r = conn.getresponse()
            return r.status, json.loads(r.read())

        q = urllib.parse.quote('iotml_http_total{job="a"}', safe="")
        status, body = get(f"/query?query={q}&time_ms={BASE_TS + 29_000}")
        assert status == 200 and body["status"] == "success"
        assert body["data"] == [{"labels": {"job": "a"},
                                 "ts_ms": BASE_TS + 29_000,
                                 "value": 290.0}]

        q = urllib.parse.quote('rate(iotml_http_total{job="a"}[10s])',
                               safe="")
        status, body = get(f"/query?query={q}&time_ms={BASE_TS + 29_000}")
        assert status == 200
        assert body["data"][0]["value"] == pytest.approx(10.0)

        q = urllib.parse.quote("iotml_http_total", safe="")
        status, body = get(
            f"/query_range?query={q}&start_ms={BASE_TS}"
            f"&end_ms={BASE_TS + 20_000}&step_ms=10000")
        assert status == 200 and len(body["data"]) == 2
        for s in body["data"]:
            assert len(s["values"]) == 3

        assert get("/query")[0] == 400                       # no expr
        bad = urllib.parse.quote("rate(x_total)", safe="")
        assert get(f"/query?query={bad}")[0] == 400          # bad expr
        assert get(f"/query_range?query={q}")[0] == 400      # no range
    finally:
        server.stop()


# ------------------------------------------- parse_prom_text round trip
TRICKY_LABELS = [
    "plain",
    'quo"te',
    "back\\slash",
    "new\nline",
    "comma,eq=brace}",
    "open{brace",
    "trailing\\",
    ' leading and trailing ',
    '\\"mixed\\" \n end}',
]


def test_parse_prom_text_roundtrip_tricky_labels_and_values():
    """Satellite (a): the exposition renderer and parser are inverses —
    escaped label values, NaN/±Inf sample values, and histogram frames
    all survive render → parse bit-faithfully."""
    reg = metrics_mod.Registry()
    c = reg.counter("rt_events_total")
    for i, v in enumerate(TRICKY_LABELS):
        c.inc(i + 1.5, label=v, idx=str(i))
    g = reg.gauge("rt_level")
    g.set(float("nan"), kind="nan")
    g.set(float("inf"), kind="hi")
    g.set(float("-inf"), kind="lo")
    h = reg.histogram("rt_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)

    types, samples = federate.parse_prom_text(reg.render())
    assert types["rt_events_total"] == "counter"
    assert types["rt_level"] == "gauge"
    assert types["rt_lat_seconds"] == "histogram"

    by_labels = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    for i, v in enumerate(TRICKY_LABELS):
        key = ("rt_events_total",
               tuple(sorted({"label": v, "idx": str(i)}.items())))
        assert key in by_labels, f"label {v!r} did not round-trip"
        assert by_labels[key] == i + 1.5
    assert math.isnan(by_labels[("rt_level", (("kind", "nan"),))])
    assert by_labels[("rt_level", (("kind", "hi"),))] == float("inf")
    assert by_labels[("rt_level", (("kind", "lo"),))] == float("-inf")
    assert by_labels[("rt_lat_seconds_bucket", (("le", "0.1"),))] == 1
    assert by_labels[("rt_lat_seconds_bucket", (("le", "+Inf"),))] == 2
    assert by_labels[("rt_lat_seconds_sum", ())] == pytest.approx(5.05)
    assert by_labels[("rt_lat_seconds_count", ())] == 2


def test_parse_prom_text_roundtrip_property_random_labels():
    """Property-style: 200 seeded random strings over an alphabet of
    exposition metacharacters all survive the round trip exactly."""
    rng = np.random.default_rng(1234)
    alphabet = np.array(list('ab"\\\n,={} \t'))
    reg = metrics_mod.Registry()
    c = reg.counter("prop_total")
    expected = {}
    for i in range(200):
        size = int(rng.integers(0, 12))
        val = "".join(rng.choice(alphabet, size=size))
        # the parser strips line-level whitespace; values differing only
        # by outer whitespace are legitimate collisions — index them
        c.inc(float(i), v=val, i=str(i))
        expected[str(i)] = (val, float(i))

    _types, samples = federate.parse_prom_text(reg.render())
    got = {l["i"]: (l["v"], v) for n, l, v in samples
           if n == "prop_total"}
    assert got == expected


def test_parse_prom_text_tolerates_garbage_lines():
    text = "\n".join([
        "# TYPE ok_total counter",
        "ok_total 3",
        "broken{unclosed 9",
        'broken{k="unterminated 9',
        "no_value",
        "# some comment",
        "",
        'ok_total{a="b"} 4 1700000000000',  # trailing timestamp ok
    ])
    types, samples = federate.parse_prom_text(text)
    assert types == {"ok_total": "counter"}
    assert samples == [("ok_total", {}, 3.0),
                       ("ok_total", {"a": "b"}, 4.0)]
