"""iotml.twin — the per-car digital twin: pure-fold state + aggregates,
idempotent redelivery, changelog rebuild from the compacted CAR_TWIN
topic, the connect REST surface, the feature-store join into live
scoring (the ISSUE-8 acceptance e2e), and partition-parallel sharding."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from iotml.core.schema import KSQL_CAR_SCHEMA
from iotml.store import StorePolicy
from iotml.stream.broker import Broker
from iotml.twin import (CHANGELOG_TOPIC, CarTwin, TwinFeatureStore,
                        TwinService, TwinTable)

IN = "SENSOR_DATA_S_AVRO"
F = len(KSQL_CAR_SCHEMA.sensor_fields)


def _publish(broker, n_ticks=6, cars=6, seed=3, partitions=2):
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=cars, seed=seed,
                                       failure_rate=0.2))
    return gen.publish(broker, IN, n_ticks=n_ticks, partitions=partitions)


# ------------------------------------------------------------ state fold
def test_car_twin_fold_aggregates_and_canonical_codec():
    t = CarTwin("car-1", partition=1)
    rows = [[1.0, 2.0], [3.0, 6.0], [5.0, 10.0]]
    for i, row in enumerate(rows):
        t.absorb(row, ts=100 + i, offset=i, failure=(i == 1), window=2)
    assert t.count == 3 and t.failures == 1 and t.offset == 2
    agg = t.aggregates()
    # window depth 2: only the last two rows aggregate
    assert agg["window_len"] == 2
    assert agg["mean"] == [4.0, 8.0]
    assert agg["min"] == [3.0, 6.0] and agg["max"] == [5.0, 10.0]
    assert agg["failure_rate"] == pytest.approx(1 / 3)
    assert len(agg["ema"]) == 2
    # canonical JSON: encode/decode/encode is byte-identical (the
    # property compacted-changelog byte-stability rides on)
    blob = t.encode()
    assert CarTwin.decode(blob).encode() == blob


def test_twin_table_idempotent_fold_and_resume_offsets():
    tbl = TwinTable(window=4)
    assert tbl.apply("a", 0, 5, [1.0], 100, False)
    # at-least-once redelivery of the same (partition, offset): dropped
    assert not tbl.apply("a", 0, 5, [9.0], 100, False)
    assert tbl.get("a").last == [1.0]
    assert tbl.apply("a", 0, 6, [2.0], 110, False)
    assert tbl.apply("b", 1, 2, [3.0], 120, True)
    assert tbl.resume_offsets() == {0: 7, 1: 3}
    # a changelog tombstone deletes the car
    tbl.apply_changelog("a", None)
    assert tbl.get("a") is None and tbl.cars() == ["b"]


def test_feature_store_vector_layout_and_cold_start():
    tbl = TwinTable()
    fs = TwinFeatureStore(tbl)
    assert fs.dim == F + 2
    # cold start: unknown car (and None key) joins the zero vector
    assert not fs.vector(None).any()
    assert not fs.vector(b"ghost").any()
    t = CarTwin("car-1")
    tbl.twins["car-1"] = t
    for i in range(10):
        t.absorb([float(i)] * F, ts=i, offset=i, failure=(i % 2 == 0))
    v = fs.vector(b"car-1")
    mean = np.mean(np.asarray(t.window, np.float64), axis=0)
    assert np.allclose(v[:F], fs.normalizer.np(mean))
    assert v[F] == pytest.approx(np.tanh(10 / 100.0))
    assert v[F + 1] == pytest.approx(0.5)
    m = fs.matrix([b"car-1", None, b"ghost"], 4)
    assert m.shape == (4, F + 2)
    assert np.array_equal(m[0], v) and not m[1:].any()


# ----------------------------------------------------- service lifecycle
def test_service_materialises_changelogs_and_rebuilds():
    b = Broker()
    b.create_topic(IN, partitions=2)
    published = _publish(b)
    svc = TwinService(b)
    while svc.pump_once():
        pass
    assert svc.applied == published and len(svc.table) == 6
    assert b.topic(CHANGELOG_TOPIC).cleanup_policy == "compact"
    assert svc.emitted > 0
    # a second incarnation rebuilds purely from the changelog — no
    # source re-read needed for the state (provenance resumes cursors)
    svc2 = TwinService(b)
    assert svc2.table.snapshot() == svc.table.snapshot()
    assert svc2.rebuilt_records > 0
    # and nothing re-folds: the stream is drained, counts stay exact
    while svc2.pump_once():
        pass
    assert svc2.table.snapshot() == svc.table.snapshot()


def test_rebuild_after_compaction_equals_snapshot(tmp_path):
    b = Broker(store_dir=str(tmp_path),
               store_policy=StorePolicy(fsync="never",
                                        segment_bytes=4 * 1024,
                                        compact_grace_ms=10 ** 9))
    b.create_topic(IN, partitions=2)
    svc = TwinService(b)
    for _ in range(8):
        _publish(b, n_ticks=1)
        svc.pump_once()
    while svc.pump_once():
        pass
    snapshot = svc.table.snapshot()
    emitted = svc.emitted
    del svc  # killed: no flush, the changelog is the only trace
    for p in range(2):
        b.store.log_for(CHANGELOG_TOPIC, p).roll()
    stats = b.run_compaction(force=True)
    assert sum(s.records_removed for s in stats.values()) > 0
    svc2 = TwinService(b)
    assert svc2.table.snapshot() == snapshot
    # the rebuild read the COMPACTED form: ~one record per car, not one
    # per update
    assert svc2.rebuilt_records <= len(snapshot) + 2 < emitted
    b.close()


def test_retire_tombstones_and_stays_retired():
    b = Broker()
    b.create_topic(IN, partitions=2)
    svc = TwinService(b)
    _publish(b)
    while svc.pump_once():
        pass
    car = svc.cars()[0]
    assert svc.retire(car) and svc.get(car) is None
    assert not svc.retire(car)  # already gone
    # the tombstone is IN the changelog, so a rebuild cannot resurrect
    svc2 = TwinService(b)
    assert car not in svc2.cars()
    (dead,) = [m for m in _drain_changelog(b) if m.key == car.encode()
               and m.value is None]
    assert dead.key == car.encode()
    # a read-only tap must refuse: tombstoning a changelog it does not
    # own would be a second writer racing the owner's table
    tap = TwinService(b, changelog=False)
    with pytest.raises(RuntimeError, match="read-only"):
        tap.retire(tap.cars()[0])


def _drain_changelog(b):
    out = []
    for p in range(b.topic(CHANGELOG_TOPIC).partitions):
        off = b.begin_offset(CHANGELOG_TOPIC, p)
        end = b.end_offset(CHANGELOG_TOPIC, p)
        while off < end:
            batch = b.fetch(CHANGELOG_TOPIC, p, off, 1 << 20)
            if not batch:
                break
            out += batch
            off = batch[-1].offset + 1
    return out


def test_partition_parallel_sharding():
    """Two service instances, one partition each: disjoint car sets,
    union == whole fleet, changelogs land in their OWN partitions."""
    b = Broker()
    b.create_topic(IN, partitions=2)
    _publish(b, cars=8)
    s0 = TwinService(b, partitions=[0], group="twin-p0")
    s1 = TwinService(b, partitions=[1], group="twin-p1")
    while s0.pump_once() or s1.pump_once():
        pass
    cars0, cars1 = set(s0.cars()), set(s1.cars())
    assert cars0 and cars1 and not (cars0 & cars1)
    assert len(cars0 | cars1) == 8
    for m in _drain_changelog(b):
        svc = s0 if m.key.decode() in cars0 else s1
        assert m.partition in svc.partitions


# ------------------------------------------------------------ REST + e2e
def test_rest_twin_endpoints():
    from iotml.connect import ConnectServer, ConnectWorker

    b = Broker()
    b.create_topic(IN, partitions=2)
    _publish(b)
    svc = TwinService(b)
    while svc.pump_once():
        pass
    srv = ConnectServer(ConnectWorker(b)).start()
    try:
        srv.attach_twin(svc)
        listing = json.loads(urllib.request.urlopen(
            f"{srv.url}/twin", timeout=5).read())
        assert listing["count"] == 6 and len(listing["cars"]) == 6
        car = listing["cars"][0]
        doc = json.loads(urllib.request.urlopen(
            f"{srv.url}/twin/{car}", timeout=5).read())
        assert doc["car"] == car
        assert set(doc["latest"]) == \
            {f.name for f in KSQL_CAR_SCHEMA.sensor_fields}
        agg = doc["aggregates"]
        assert agg["window_len"] > 0 and len(agg["mean"]) == F
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{srv.url}/twin/no-such-car",
                                   timeout=5)
        assert ei.value.code == 404
        # DELETE retires: tombstone in the changelog, 404 after
        req = urllib.request.Request(f"{srv.url}/twin/{car}",
                                     method="DELETE")
        assert urllib.request.urlopen(req, timeout=5).status == 204
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{srv.url}/twin/{car}", timeout=5)
    finally:
        srv.stop()


def test_scorer_joins_twin_features_while_rest_serves():
    """The ISSUE-8 acceptance e2e: GET /twin/<car_id> answers latest
    state + rolling aggregates over connect REST WHILE a StreamScorer
    joins the same twin's features onto the live window it scores."""
    from iotml.connect import ConnectServer, ConnectWorker
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import DenseAutoencoder
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    b = Broker()
    b.create_topic(IN, partitions=2)
    published = _publish(b, n_ticks=8)
    svc = TwinService(b)
    while svc.pump_once():
        pass
    fs = TwinFeatureStore(svc)

    # the joined layout: F live sensor columns + fs.dim twin features
    model = DenseAutoencoder(input_dim=F + fs.dim)
    trainer = Trainer(model)
    trainer._ensure_state(np.zeros((100, F + fs.dim), np.float32))
    consumer = StreamConsumer(b, [f"{IN}:{p}:0" for p in range(2)],
                              group="twin-scorer")
    batches = SensorBatches(consumer, batch_size=100, keep_keys=True)
    out = OutputSequence(b, "model-predictions", partition=0)
    scorer = StreamScorer(
        model, trainer.state.params, batches, out,
        feature_store=fs,
        # the verdict mask was calibrated on the LIVE columns; the
        # widening branch must keep the joined twin columns out of it
        verdict_mask=np.ones((F,), bool), threshold=10.0)

    srv = ConnectServer(ConnectWorker(b)).start()
    try:
        srv.attach_twin(svc)
        scored = scorer.score_available()
        car = svc.cars()[0]
        doc = json.loads(urllib.request.urlopen(
            f"{srv.url}/twin/{car}", timeout=5).read())
    finally:
        srv.stop()
    assert scored == published
    assert b.end_offset("model-predictions", 0) == published
    # the join was real: the materialised car's feature vector is
    # nonzero (a zero vector would mean the scorer joined nothing)
    assert fs.vector(car.encode()).any()
    assert doc["aggregates"]["window_len"] > 0


# ------------------------------------------------------------- the drill
def test_twin_rebuild_drill_smoke():
    from iotml.twin.drill import run_twin_rebuild_drill

    report = run_twin_rebuild_drill(seed=11, records=300)
    assert report.ok, [i.detail for i in report.invariants if not i.ok]
    assert report.compaction_removed > 0
